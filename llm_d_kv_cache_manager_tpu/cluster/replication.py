"""Journal-fed replication: followers keep standby slices warm.

A :class:`ReplicationFollower` keeps a local index current with one
primary replica by (1) an optional warm-sync bootstrap — the primary's
``sync_snapshot`` RPC returns a journal boundary, its per-pod seq
watermarks, and a dump taken after the boundary — and (2) tailing the
primary's journal segments from that boundary with
``persistence.journal.tail`` (torn tails hold, rotation and compaction
are followed; see the tail contract).  Numbered records strictly below
the bootstrap watermark are skipped, mirroring recovery's replay rule;
unnumbered records (seq 0 — e.g. router-fed applies, whose publisher
seq died at the Index interface) always replay.  Replay is idempotent
either way.

**Standby slices.**  A follower normally applies only the keys it
would inherit if the primary died: ``standby_record_filter`` trims
each record to the keys whose rendezvous runner-up — computed on the
FULL configured ring, which never changes version — is this replica.
When the membership then removes the dead primary, the live ring's new
owner for those keys IS this replica (the rendezvous property), so the
failed-over slice is warm up to the follower's last poll: the pinned
hit-rate dip is bounded by ``poll_interval_s`` of traffic plus
anything holding at a torn tail.

Journal directories are the replication channel: in-process clusters
(tests, bench, smoke) share a tmpdir; multi-process deployments put
them on the shared filesystem the offload tier already mounts
(docs/replication.md).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from llm_d_kv_cache_manager_tpu.cluster.replica import (
    decode_entries,
)
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS, safe_label
from llm_d_kv_cache_manager_tpu.persistence.journal import (
    OP_ADD,
    OP_PURGE,
    JournalRecord,
    TailPosition,
    tail,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("cluster.replication")

# Leaf lock: position/stats bookkeeping only; applies into the local
# index happen outside it.
# kvlint: lock-order: ReplicationFollower._lock ascending
lockorder.declare_ascending("ReplicationFollower._lock")


def standby_record_filter(
    full_ring: HashRing, self_id: str
) -> Callable[[JournalRecord], Optional[JournalRecord]]:
    """Trim records to this replica's standby slice.

    Keeps the (engine_key, request_key) pairs whose request key lists
    this replica among its top-2 rendezvous owners on the FULL ring —
    as primary (re-applying local state is idempotent) or as standby
    (the failover inheritance).  Evict records carry no request keys
    and apply unconditionally: evicting an absent engine key is a
    no-op, and filtering them by engine-key ownership could strand a
    standby admission the evict was meant to clear.
    """

    def filter_record(
        record: JournalRecord,
    ) -> Optional[JournalRecord]:
        if record.op != OP_ADD or not record.request_keys:
            return record
        aligned = len(record.engine_keys) == len(record.request_keys)
        if aligned and not record.entries:
            # Mappings-only record: the standby must inherit it when it
            # stands by for EITHER side — the engine-key owner serves
            # get_request_key after a failover, and without the mapping
            # the router would classify post-failover evictions as
            # "already gone" and leave stale entries scoring forever.
            wanted = [
                i
                for i, (ek, rk) in enumerate(
                    zip(record.engine_keys, record.request_keys)
                )
                if self_id in full_ring.owners(rk, 2)
                or self_id in full_ring.owners(ek, 2)
            ]
        else:
            wanted = [
                i
                for i, rk in enumerate(record.request_keys)
                if self_id in full_ring.owners(rk, 2)
            ]
        if not wanted:
            return None
        if len(wanted) == len(record.request_keys):
            return record
        engine_keys = (
            [record.engine_keys[i] for i in wanted]
            if aligned
            else record.engine_keys
        )
        return JournalRecord(
            op=record.op,
            pod_identifier=record.pod_identifier,
            seq=record.seq,
            ts_ns=record.ts_ns,
            engine_keys=engine_keys,
            request_keys=[record.request_keys[i] for i in wanted],
            entries=record.entries,
        )

    return filter_record


def apply_record(index: Index, record: JournalRecord) -> bool:
    """Replay one journal record as the index call it logs; returns
    False when the record shape has nothing applicable (e.g. a batched
    admission against a backend without the batched surface)."""
    try:
        if record.op == OP_PURGE:
            # Replay in journal order so a standby slice never
            # resurrects entries the primary purged.
            index.purge_pod(record.pod_identifier)
            return True
        if record.op == OP_ADD:
            if not record.request_keys:
                return False
            if record.engine_keys and not record.entries:
                # Mappings-only record (the router's eager
                # add_mappings publication).
                add_mappings = getattr(index, "add_mappings", None)
                if not callable(add_mappings):
                    return False
                add_mappings(record.engine_keys, record.request_keys)
                return True
            if not record.entries:
                return False
            if record.engine_keys and len(record.engine_keys) == len(
                record.request_keys
            ):
                index.add(
                    record.engine_keys,
                    record.request_keys,
                    record.entries,
                )
                return True
            # Batched admission (no engine keys on the record).
            add_batch = getattr(index, "add_entries_batch", None)
            if not callable(add_batch):
                return False
            add_batch([(record.request_keys, record.entries)])
            return True
        applied = False
        for engine_key in record.engine_keys:
            index.evict(engine_key, record.entries)
            applied = True
        return applied
    except (KeyError, ValueError) as exc:
        # Same tolerance as recovery: a replayed op can race LRU
        # bounds on the standby side.
        logger.debug("skipping unreplayable record: %s", exc)
        return False


class ReplicationFollower:
    """Tails one primary's journal directory into a local index."""

    def __init__(
        self,
        peer_id: str,
        journal_dir: str,
        index: Index,
        record_filter: Optional[
            Callable[[JournalRecord], Optional[JournalRecord]]
        ] = None,
        poll_interval_s: float = 0.2,
        max_records_per_poll: int = 4096,
        purge_scope: Optional[Callable[[int], bool]] = None,
    ) -> None:
        if poll_interval_s <= 0:
            raise ValueError("poll interval must be positive")
        self.peer_id = peer_id
        self.journal_dir = journal_dir
        self.index = index
        self.record_filter = record_filter
        self.poll_interval_s = poll_interval_s
        self.max_records_per_poll = max(1, max_records_per_poll)
        # Slice scope for replaying the peer's OP_PURGE records (keys
        # the PEER's journal is authoritative for — its primary slice).
        # A pod-wide purge replayed against the whole local index would
        # wipe admissions this replica applied to its OWN slice after
        # the purge (every replica executes the router's purge directly
        # and journals it; each stream's purge must only touch the
        # slice that stream owns).  None falls back to the pod-wide
        # purge — correct for single-stream uses like disaster replay.
        self.purge_scope = purge_scope
        self._lock = lockorder.tracked(
            threading.Lock(), "ReplicationFollower._lock"
        )
        self._position: Optional[TailPosition] = None  # guarded-by: _lock
        self._watermarks: Dict[str, int] = {}  # guarded-by: _lock
        self._applied = 0  # guarded-by: _lock
        self._skipped = 0  # guarded-by: _lock
        self._last_lag = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- bootstrap ------------------------------------------------------

    def bootstrap(self, transport) -> int:
        """Warm-sync from the primary's ``sync_snapshot``: restore the
        dump (filtered to the standby slice), remember the watermarks,
        and park the tail cursor at the journal boundary.  Returns
        block keys restored."""
        boundary, raw_watermarks, raw_blocks, raw_map = transport.call(
            "sync_snapshot", []
        )
        block_entries = []
        for key, raw_entries in raw_blocks:
            entries = list(decode_entries(raw_entries))
            if self.record_filter is not None:
                trimmed = self.record_filter(
                    JournalRecord(
                        op=OP_ADD,
                        pod_identifier="",
                        seq=0,
                        ts_ns=0,
                        engine_keys=[],
                        request_keys=[key],
                        entries=entries,
                    )
                )
                if trimmed is None:
                    continue
            block_entries.append((key, entries))
        restored = self.index.restore_entries(
            block_entries, [(ek, rk) for ek, rk in raw_map]
        )
        with self._lock:
            self._position = TailPosition(boundary, 0)
            self._watermarks = {
                str(pod): int(seq) for pod, seq in raw_watermarks
            }
        logger.info(
            "follower of %s bootstrapped: %d block keys, journal "
            "boundary %d",
            self.peer_id,
            restored,
            boundary,
        )
        return restored

    # -- sync loop ------------------------------------------------------

    def sync_once(self) -> int:
        """One tail poll: read new records, apply the standby slice;
        returns records read (the lag this poll drained).  Callable
        directly so tests and the smoke never sleep-poll."""
        with self._lock:
            position = self._position
            watermarks = dict(self._watermarks)
        records, new_position = tail(
            self.journal_dir,
            position,
            max_records=self.max_records_per_poll,
        )
        applied = skipped = 0
        for record in records:
            watermark = watermarks.get(record.pod_identifier)
            # Strictly-below skip, mirroring recovery: equal-seq
            # records straddle the boundary and replay idempotently.
            if (
                watermark is not None
                and record.seq > 0
                and record.seq < watermark
            ):
                skipped += 1
                continue
            if self.record_filter is not None:
                record = self.record_filter(record)
                if record is None:
                    skipped += 1
                    continue
            if record.op == OP_PURGE and self._scoped_purge(record):
                applied += 1
                continue
            if apply_record(self.index, record):
                applied += 1
            else:
                skipped += 1
        with self._lock:
            # sync_once is single-consumer (one sync thread; tests
            # call it inline, never concurrently) — the lock only
            # publishes position/stats to status readers, so the
            # read-process-write spanning two acquisitions cannot
            # interleave with another advance.
            self._position = new_position  # kvlint: atomic-ok
            self._applied += applied
            self._skipped += skipped
            self._last_lag = len(records)
        peer = safe_label(self.peer_id)
        METRICS.cluster_replica_lag.labels(peer=peer).set(len(records))
        if applied:
            METRICS.cluster_replication_applied.labels(peer=peer).inc(
                applied
            )
        return len(records)

    def _scoped_purge(self, record: JournalRecord) -> bool:
        """Replay a peer's purge against its slice only; returns False
        when unscoped (caller falls back to the pod-wide purge)."""
        if self.purge_scope is None:
            return False
        purge_keys = getattr(self.index, "purge_pod_keys", None)
        list_keys = getattr(self.index, "request_keys", None)
        if not callable(purge_keys) or not callable(list_keys):
            return False
        # Keys-only walk — a full dump_entries here would serialize
        # every entry list just to throw it away, per replayed purge.
        candidates = [
            key for key in list_keys() if self.purge_scope(key)
        ]
        if candidates:
            purge_keys(record.pod_identifier, candidates)
        return True

    def start(self) -> None:
        if self._thread is not None:
            return
        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=self._run,
            name=f"kvtpu-cluster-follow-{self.peer_id}",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                drained = self.sync_once()
            except Exception:  # noqa: BLE001 — the follower must survive
                logger.exception(
                    "follower of %s failed a sync poll", self.peer_id
                )
                drained = 0
            if drained < self.max_records_per_poll:
                # Caught up (or holding at a torn tail): wait a beat.
                self._stop.wait(self.poll_interval_s)

    def status(self) -> dict:
        with self._lock:
            return {
                "peer": self.peer_id,
                "applied": self._applied,
                "skipped": self._skipped,
                "last_poll_lag": self._last_lag,
                "position": (
                    [self._position.segment_id, self._position.offset]
                    if self._position is not None
                    else None
                ),
            }
