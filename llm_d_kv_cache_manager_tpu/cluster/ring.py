"""Deterministic, versioned rendezvous hash ring over block-key space.

Rendezvous (highest-random-weight) hashing instead of a virtual-node
token ring: every ``(key, member)`` pair gets a deterministic 64-bit
weight and the key's owner is the member with the highest weight.  The
properties the cluster leans on fall out of the construction:

* **Determinism across processes.**  Weights are pure functions of the
  member id string and the key integer (blake2b member seed + a
  splitmix64-style finalizer) — never Python's seeded ``hash()`` — so
  every router and replica computes the same ownership, whatever its
  ``PYTHONHASHSEED`` (property-pinned by a subprocess test).
* **Minimal disruption.**  Removing a member reassigns exactly the keys
  it owned — each to its rendezvous runner-up — and adding a member
  steals ~1/N of the key space, spread evenly over the survivors; no
  other key moves.  This is also what makes failover warm: the
  runner-up (``owners(key, 2)[1]``) is the key's standby, and a
  follower syncing the standby slice holds precisely the keys it will
  inherit (see ``replication.py``).
* **Versioning.**  Membership changes produce a NEW ring with
  ``version + 1``; the ring itself is immutable, so readers snapshot it
  once per operation and per-version ownership caches stay sound.

Block keys are FNV-64 outputs (uniform already), but the weight mix
must decorrelate keys that differ in few bits AND decorrelate members,
hence the two-level mix below.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence, Tuple

__all__ = ["HashRing"]

_MASK64 = (1 << 64) - 1
# splitmix64 finalizer constants (Steele et al.); full-avalanche on
# 64-bit inputs, cheap enough for a per-key per-member Python loop.
_C1 = 0xFF51AFD7ED558CCD
_C2 = 0xC4CEB9FE1A85EC53


def _mix64(x: int) -> int:
    x &= _MASK64
    x ^= x >> 33
    x = (x * _C1) & _MASK64
    x ^= x >> 33
    x = (x * _C2) & _MASK64
    x ^= x >> 33
    return x


def _member_seed(member: str) -> int:
    """Stable 64-bit seed for a member id (process-independent)."""
    digest = hashlib.blake2b(member.encode(), digest_size=8).digest()
    return int.from_bytes(digest, "big")


class HashRing:
    """Immutable rendezvous ring over a set of replica ids."""

    __slots__ = ("_members", "_seeds", "_version")

    def __init__(self, members: Sequence[str], version: int = 0) -> None:
        unique = sorted(set(members))
        if not unique:
            raise ValueError("a hash ring needs at least one member")
        for member in unique:
            if not member:
                raise ValueError("empty replica id")
        self._members: Tuple[str, ...] = tuple(unique)
        self._seeds: Tuple[int, ...] = tuple(
            _member_seed(m) for m in unique
        )
        self._version = version

    @property
    def members(self) -> Tuple[str, ...]:
        return self._members

    @property
    def version(self) -> int:
        return self._version

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    # -- ownership ------------------------------------------------------

    def owner(self, key: int) -> str:
        """The member with the highest rendezvous weight for ``key``."""
        mixed = _mix64(key)
        best = None
        best_weight = -1
        for member, seed in zip(self._members, self._seeds):
            weight = _mix64(mixed ^ seed)
            if weight > best_weight:
                best_weight = weight
                best = member
        return best  # type: ignore[return-value] — members is non-empty

    def owners(self, key: int, n: int = 2) -> List[str]:
        """The top-``n`` members by weight: ``[primary, standby, ...]``.

        ``owners(key, 2)[1]`` is the key's failover target — remove the
        primary from the ring and ``owner(key)`` on the new ring IS
        that runner-up (the rendezvous property replication relies on).
        Weight ties are impossible in practice (64-bit), but broken by
        member id for bit-determinism anyway.
        """
        mixed = _mix64(key)
        ranked = sorted(
            (
                (_mix64(mixed ^ seed), member)
                for member, seed in zip(self._members, self._seeds)
            ),
            key=lambda pair: (-pair[0], pair[1]),
        )
        return [member for _, member in ranked[:n]]

    # -- membership changes (new ring, version + 1) ---------------------

    def without(self, member: str) -> "HashRing":
        if member not in self._members:
            return self
        remaining = [m for m in self._members if m != member]
        return HashRing(remaining, version=self._version + 1)

    def with_member(self, member: str) -> "HashRing":
        if member in self._members:
            return self
        return HashRing(
            list(self._members) + [member], version=self._version + 1
        )

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"HashRing(v{self._version}, "
            f"members={list(self._members)!r})"
        )
