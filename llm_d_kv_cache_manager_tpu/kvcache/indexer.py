"""The Indexer: orchestration of the scoring read path.

``get_pod_scores(prompt, model, pods)`` answers the scheduler's question —
*which pod holds the longest consecutive prefix of this prompt's KV
blocks?* — by composing the subsystem stack (reference:
pkg/kvcache/indexer.go:124-165):

    tokenize (pool + prefix store [+ chat render])
      -> token chain -> request block keys (ChunkedTokenDatabase)
      -> index lookup (pluggable backend)
      -> longest-prefix tier-weighted score

One ``Config`` composes every module's config with defaults, so embedding
applications construct the whole stack from a single literal.

Read-path fast lane (docs/performance.md): by default ``get_pod_scores``
runs a chunked drive of the stack — the prefix store returns memoized
block keys alongside tokens (a multi-turn conversation only hashes its
new suffix), and hashing + index lookups proceed in chunks that stop as
soon as the prefix chain is dead for every candidate pod (an 8k-token
cold prompt stops paying for its unreachable suffix).  Scores are
bit-identical to the straight-line path (pinned by property tests);
``READ_PATH_FAST_LANE=0`` or ``IndexerConfig.read_path_fast_lane=False``
restores the straight-line path.

Against a backend that fans lookups out over the wire (the cluster
``RemoteIndex``), the chunked drive additionally pipelines: chunk N+1
is hashed and dispatched while chunk N's owner RPCs are in flight, and
predicted-deep chains (score memo / analytics ledger) speculate further
ahead (``CLUSTER_PIPELINE_DEPTH`` / ``CLUSTER_SPECULATE``; scores stay
bit-identical — docs/replication.md).
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    Index,
    IndexConfig,
    new_index,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessor,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.scorer import (
    LongestPrefixScorer,
    ScorerConfig,
    new_scorer,
)
from llm_d_kv_cache_manager_tpu.obs.trace import (
    current_trace,
    span as obs_span,
)
from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (
    ApplyChatTemplateRequest,
    ChatTemplatingProcessor,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (
    LRUStoreConfig,
    LRUTokenStore,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    CompositeTokenizer,
    LocalFastTokenizer,
    Tokenizer,
    TransformersTokenizer,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger, trace

logger = get_logger("kvcache.indexer")

# Block keys hashed + looked up per fast-lane round trip; the early-exit
# granularity (a dead chain stops within one chunk of the break).
DEFAULT_LOOKUP_CHUNK = 32

# Entries in the request score memo (exact-prompt results validated by
# the index's per-shard version vector); 0 disables.
DEFAULT_SCORE_MEMO = 256

# Chunks the fast lane keeps in flight against an async-capable index
# backend (the cluster RemoteIndex): chunk N+1 is hashed and dispatched
# while chunk N's owner RPCs are on the wire.  0 forces the sequential
# drive (the bit-identical parity oracle; docs/replication.md).
DEFAULT_PIPELINE_DEPTH = 3

# One-shot guard for the memo-self-disable warning (every Indexer over
# the same memo-incapable backend hits the same condition; one line per
# process is the signal, N lines is noise).
_MEMO_DISABLED_WARNED = False


def _env_fast_lane_default() -> Optional[bool]:
    raw = os.environ.get("READ_PATH_FAST_LANE")
    if raw is None:
        return None
    return raw.strip().lower() not in ("0", "false", "off")


def _env_cache_stats_default() -> bool:
    """CACHESTATS: "0"/"false"/"off" disables the hit-attribution
    ledger; unset/anything else keeps it on (sampling is governed
    separately by CACHESTATS_SAMPLE_RATE — docs/observability.md)."""
    raw = os.environ.get("CACHESTATS")
    if raw is None:
        return True
    return raw.strip().lower() not in ("0", "false", "off")


def _env_load_blend_default() -> float:
    """LOAD_BLEND: coefficient folding per-pod queue depth into
    scores (``score / (1 + blend * depth)``); 0 (the default)
    disables blending and keeps scores bit-identical to today's."""
    raw = os.environ.get("LOAD_BLEND", "")
    if not raw:
        return 0.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        logger.warning("invalid LOAD_BLEND=%r; using 0", raw)
        return 0.0


def _env_score_memo_default() -> Optional[int]:
    """READ_PATH_SCORE_MEMO: "0"/"false"/"off" disables, a positive
    integer sizes the memo, unset defers to the config default."""
    raw = os.environ.get("READ_PATH_SCORE_MEMO")
    if raw is None:
        return None
    text = raw.strip().lower()
    if text in ("0", "false", "off"):
        return 0
    try:
        return max(0, int(text))
    except ValueError:
        return DEFAULT_SCORE_MEMO


def _env_pipeline_depth_default() -> int:
    """CLUSTER_PIPELINE_DEPTH: fast-lane chunks in flight at once when
    the index backend exposes ``lookup_chain_async`` (the cluster
    RemoteIndex); 0 keeps the strictly sequential chunk drive — the
    bit-identical parity oracle (docs/replication.md)."""
    raw = os.environ.get("CLUSTER_PIPELINE_DEPTH", "")
    if not raw:
        return DEFAULT_PIPELINE_DEPTH
    try:
        return max(0, int(raw))
    except ValueError:
        logger.warning(
            "invalid CLUSTER_PIPELINE_DEPTH=%r; using %d",
            raw,
            DEFAULT_PIPELINE_DEPTH,
        )
        return DEFAULT_PIPELINE_DEPTH


def _env_speculate_default() -> bool:
    """CLUSTER_SPECULATE: "0"/"false"/"off" restricts the pipeline to
    plain one-ahead overlap; on (the default) lets a predicted-deep
    chain (score memo / analytics ledger) dispatch further ahead."""
    raw = os.environ.get("CLUSTER_SPECULATE")
    if raw is None:
        return True
    return raw.strip().lower() not in ("0", "false", "off")


class _ScoreMemoEntry:
    """One memoized scoring result: the scores computed by a full
    fast-lane walk, the two validators that prove a re-walk would
    reproduce them — the index version vector captured BEFORE that walk
    (equal vectors at hit time mean no score-relevant mutation landed
    since) and the exact token stream tokenization served the walk
    (compared by value: a prefix-store chunk overwritten by an
    overlapping prompt's different split can change the served token
    VALUES while preserving their count, and stale tokens mean stale
    block keys) — and the chain keys the walk consumed (touched on
    every hit so LRU recency, hence eviction order, stays identical to
    the walk the memo elides).  Entries also carry the walk's analytics
    attribution (family key, matched blocks, tier split) so a memo hit
    replays the same ledger record the elided walk would have
    produced."""

    __slots__ = (
        "scores",
        "version",
        "tokens",
        "touch_keys",
        "max_pod_hits",
        "family",
        "matched_blocks",
        "tier_counts",
    )

    def __init__(
        self,
        scores: Dict[str, float],
        version: tuple,
        tokens: tuple,
        touch_keys: tuple,
        max_pod_hits: int,
        family: Optional[int] = None,
        matched_blocks: int = 0,
        tier_counts: Optional[Dict[str, int]] = None,
    ) -> None:
        self.scores = scores
        self.version = version
        self.tokens = tokens
        self.touch_keys = touch_keys
        self.max_pod_hits = max_pod_hits
        self.family = family
        self.matched_blocks = matched_blocks
        self.tier_counts = tier_counts


# Traced provenance attr is bounded: past this many candidate pods the
# attr keeps the best matchers (the ones a slow-trace reader needs).
_PROVENANCE_MAX_PODS = 32


def _provenance_attr(chain) -> Dict[str, dict]:
    """Per-pod ``{blocks_matched, break_index}`` span attribute for a
    traced scoring request (cross-link: a slow trace in /debug/traces
    is diagnosable without re-issuing ``?explain=1``), size-capped."""
    provenance = chain.provenance()
    if len(provenance) <= _PROVENANCE_MAX_PODS:
        return provenance
    top = sorted(
        provenance.items(),
        key=lambda item: (-item[1]["blocks_matched"], item[0]),
    )[:_PROVENANCE_MAX_PODS]
    return dict(top)


def _ledger_record(ledger, family, model_name, total, matched, tiers) -> None:
    """Analytics must never fail a scoring request: a ledger bug is
    loud (logged with stack) but non-fatal."""
    try:
        ledger.record(family, model_name, total, matched, tiers)
    except Exception:  # noqa: BLE001 - scoring outlives analytics bugs
        logger.exception("cache-stats record failed")


@dataclass
class IndexerConfig:
    prefix_store_config: LRUStoreConfig = field(default_factory=LRUStoreConfig)
    token_processor_config: TokenProcessorConfig = field(
        default_factory=TokenProcessorConfig
    )
    kvblock_index_config: IndexConfig = field(default_factory=IndexConfig)
    scorer_config: ScorerConfig = field(default_factory=ScorerConfig)
    tokenizers_pool_config: TokenizationPoolConfig = field(
        default_factory=TokenizationPoolConfig
    )
    # Directory searched by the local tokenizer backend; None disables it.
    local_tokenizers_dir: Optional[str] = None
    # UDS path of a tokenizer sidecar (services/uds_tokenizer); None
    # disables that backend.  Composite order mirrors the reference's
    # local -> uds -> hf fallback chain (pkg/tokenization/pool.go:97-145).
    uds_tokenizer_path: Optional[str] = None
    # Read-path fast lane (memoized block keys + chunked early-exit
    # lookup).  None resolves from READ_PATH_FAST_LANE (default on);
    # scores are identical either way (docs/performance.md).
    read_path_fast_lane: Optional[bool] = None
    # Keys hashed + looked up per fast-lane chunk.
    lookup_chunk_size: int = DEFAULT_LOOKUP_CHUNK
    # Entries in the request score memo (fast lane only): a repeat of
    # an exact prompt returns its memoized scores when the index's
    # per-shard version vector is unchanged since they were computed —
    # any add/evict/purge/restore invalidates.  0 disables; None
    # resolves from READ_PATH_SCORE_MEMO (default 256).  Requires an
    # index backend exposing version_vector/touch_chain (the in-memory
    # backend and the cluster RemoteIndex; others silently run without
    # the memo).  Entries pin their prompt strings, so memory is
    # O(size x prompt length).
    score_memo_size: Optional[int] = None
    # Read-path chunk pipelining (docs/replication.md): against a
    # backend exposing lookup_chain_async (the cluster RemoteIndex),
    # the fast lane keeps up to this many chunks in flight — chunk N+1
    # is hashed and dispatched while chunk N's owner RPCs are on the
    # wire, and a chain dead for every pod drops the speculative
    # in-flight results on the floor.  0 forces the sequential drive
    # (the bit-identical parity oracle); None resolves from
    # CLUSTER_PIPELINE_DEPTH (default 3).  Scores are bit-identical
    # either way (tests/test_cluster_pipeline.py pins it).
    pipeline_depth: Optional[int] = None
    # Chain speculation: depth > 1 dispatch ahead is gated on a
    # likely-alive-deep prediction (the score memo's last matched
    # depth for this exact prompt, or the analytics ledger's average
    # matched blocks for the family).  None resolves from
    # CLUSTER_SPECULATE (default on); False limits the pipeline to
    # one-ahead overlap.
    speculate: Optional[bool] = None
    # Cache-efficiency analytics (analytics/ledger.py): every scored
    # request feeds the hit-attribution ledger, outside index locks,
    # gated by CACHESTATS_SAMPLE_RATE.  None resolves from the
    # CACHESTATS env knob (default on); False disables.
    cache_stats: Optional[bool] = None
    # Predictive tiering (tiering/engine.py): when a PolicyEngine is
    # attached (constructor arg or set_policy_engine), sampled scoring
    # requests feed its PolicyFeed (outside index locks) and the
    # explain surface carries compute-or-load advice.  Config-only
    # construction stays None; the engine is wired by the embedding
    # application (TIERING=1 in the HTTP service).
    #
    # Load-blended scoring (docs/transfer.md): when callers pass
    # per-pod queue depths to get_pod_scores, each score is divided by
    # ``1 + load_blend * depth`` so the router and the transfer
    # planner's "holder overloaded" trigger share one signal.  None
    # resolves from LOAD_BLEND (default 0.0 = off; with no pod_loads
    # or a zero coefficient the returned dict is the identical object
    # the unblended path computes).
    load_blend: Optional[float] = None


class Indexer:
    """Composes the read-path stack; see module docstring."""

    def __init__(
        self,
        config: Optional[IndexerConfig] = None,
        token_processor: Optional[TokenProcessor] = None,
        tokenizer: Optional[Tokenizer] = None,
        chat_processor: Optional[ChatTemplatingProcessor] = None,
        cache_stats_ledger=None,
        policy_engine=None,
        kv_block_index: Optional[Index] = None,
        capture_recorder=None,
    ) -> None:
        self.config = config or IndexerConfig()
        self.token_processor = token_processor or ChunkedTokenDatabase(
            self.config.token_processor_config
        )
        # An injected backend wins over config — the remote/cluster
        # unlock (cluster/remote_index.py) and any embedding that
        # builds its own Index: the whole read path only ever speaks
        # the lookup/lookup_chain contract, so a remote backend slots
        # in unchanged (the score memo self-disables when the backend
        # lacks version_vector/touch_chain, see below).
        self.kv_block_index: Index = (
            kv_block_index
            if kv_block_index is not None
            else new_index(self.config.kvblock_index_config)
        )
        self.scorer: LongestPrefixScorer = new_scorer(
            self.config.scorer_config
        )
        self.prefix_store = LRUTokenStore(self.config.prefix_store_config)
        self.chat_processor = chat_processor or ChatTemplatingProcessor()

        fast_lane = self.config.read_path_fast_lane
        if fast_lane is None:
            env_default = _env_fast_lane_default()
            fast_lane = True if env_default is None else env_default
        if fast_lane and not (
            hasattr(self.token_processor, "block_size")
            and callable(
                getattr(self.token_processor, "extend_block_keys", None)
            )
        ):
            # A custom TokenProcessor only promises the Protocol
            # (tokens_to_kv_block_keys); the fast lane needs the
            # chunked-resume surface, so fall back to the straight
            # path rather than crash on the first request.
            logger.info(
                "token processor %s lacks the fast-lane surface "
                "(block_size/extend_block_keys); using the straight "
                "read path",
                type(self.token_processor).__name__,
            )
            fast_lane = False
        self._fast_lane = fast_lane
        if self.config.lookup_chunk_size <= 0:
            raise ValueError("lookup_chunk_size must be positive")
        self._lookup_chunk = self.config.lookup_chunk_size
        pipeline_depth = self.config.pipeline_depth
        if pipeline_depth is None:
            pipeline_depth = _env_pipeline_depth_default()
        self._pipeline_depth = max(0, int(pipeline_depth))
        speculate = self.config.speculate
        if speculate is None:
            speculate = _env_speculate_default()
        self._speculate = bool(speculate)
        # Hash-space identity for block-key memoization; None when the
        # token processor does not expose one (custom TokenProcessor
        # implementations) — the fast lane then runs without memo.
        self._key_space = getattr(self.token_processor, "key_space", None)
        # A metrics-wrapped index records lookups per call; the fast
        # lane makes one call per chunk, so it records ONE
        # request-granular observation itself instead (see
        # InstrumentedIndex.record_chain_lookup).
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import (
            InstrumentedIndex,
        )

        self._record_chain_lookup = (
            InstrumentedIndex.record_chain_lookup
            if isinstance(self.kv_block_index, InstrumentedIndex)
            else None
        )

        memo_size = self.config.score_memo_size
        if memo_size is None:
            env_memo = _env_score_memo_default()
            memo_size = DEFAULT_SCORE_MEMO if env_memo is None else env_memo
        from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache

        self._score_memo: Optional[LRUCache] = None
        memo_wanted = self._fast_lane and memo_size > 0
        memo_supported = callable(
            getattr(self.kv_block_index, "version_vector", None)
        ) and callable(getattr(self.kv_block_index, "touch_chain", None))
        if memo_wanted and memo_supported:
            self._score_memo = LRUCache(memo_size)
        # The silent self-disable was invisible to operators: a
        # deployment over a backend without version_vector pays the
        # full walk on warm repeats while a memo-capable one (the
        # in-memory backend, the cluster RemoteIndex) memoizes — the
        # gauge + one-shot warning make that difference diagnosable
        # (docs/observability.md).  The gauge LATCHES to 1
        # (never written back to 0): it is process-wide, and a later
        # memo-capable Indexer construction — embedders and tests
        # build several — must not wipe the serving indexer's signal.
        if memo_wanted and not memo_supported:
            from llm_d_kv_cache_manager_tpu.metrics.collector import (
                METRICS,
            )

            METRICS.score_memo_disabled.set(1)
            global _MEMO_DISABLED_WARNED
            if not _MEMO_DISABLED_WARNED:
                _MEMO_DISABLED_WARNED = True
                logger.warning(
                    "request score memo disabled: index backend %s "
                    "lacks version_vector/touch_chain — warm repeat "
                    "prompts pay the full walk; "
                    "kvtpu_score_memo_disabled=1",
                    type(self.kv_block_index).__name__,
                )

        # Hit-attribution ledger (analytics/ledger.py): an explicit
        # ledger always wins (tests, bench A/B share one ledger across
        # indexers); otherwise construct from env unless disabled.
        # Only a ledger this Indexer constructed is closed by its
        # shutdown — an injected one belongs to the caller.
        self.cache_stats = cache_stats_ledger
        self._owns_ledger = False
        if self.cache_stats is None:
            enabled = self.config.cache_stats
            if enabled is None:
                enabled = _env_cache_stats_default()
            if enabled:
                from llm_d_kv_cache_manager_tpu.analytics.ledger import (
                    CacheStatsLedger,
                )

                self.cache_stats = CacheStatsLedger()
                self._owns_ledger = True

        # Input flight recorder (obs/capture.py): every scored request
        # lands in the capture ring — model, SERVED token chain, pod
        # filter, returned scores — after scoring, outside index
        # locks, so an incident bundle can replay the read path to a
        # divergence (obs/replay.py).  None (the default and the
        # CAPTURE=0 path) costs one ``is None`` check per request.
        self.capture = capture_recorder

        # Predictive-tiering hook (tiering/engine.py): sampled scoring
        # requests feed the engine's PolicyFeed, and explain carries
        # compute-or-load advice.  Attached, never constructed here.
        self.policy_engine = None
        if policy_engine is not None:
            self.set_policy_engine(policy_engine)

        # KV-transfer planning hook (transfer/engine.py): the planned
        # scoring variant and the explain surface carry transfer
        # directives when an engine is attached (set_transfer_engine;
        # TRANSFER=1 in the HTTP service).  Attached, never
        # constructed here — same contract as the policy engine.
        self.transfer_engine = None
        load_blend = self.config.load_blend
        if load_blend is None:
            load_blend = _env_load_blend_default()
        self._load_blend = max(0.0, float(load_blend))

        if tokenizer is None:
            backends: List[Tokenizer] = []
            if self.config.local_tokenizers_dir:
                backends.append(
                    LocalFastTokenizer(self.config.local_tokenizers_dir)
                )
            if self.config.uds_tokenizer_path:
                from llm_d_kv_cache_manager_tpu.tokenization.uds_tokenizer import (  # noqa: E501 - lazy: grpc only when configured
                    UdsTokenizer,
                )

                backends.append(UdsTokenizer(self.config.uds_tokenizer_path))
            backends.append(TransformersTokenizer())
            tokenizer = CompositeTokenizer(backends)
        self.tokenization_pool = TokenizationPool(
            tokenizer,
            self.prefix_store,
            self.config.tokenizers_pool_config,
            chat_processor=self.chat_processor,
        )

    def run(self) -> None:
        """Start background workers (idempotent)."""
        self.tokenization_pool.start()

    def shutdown(self) -> None:
        self.tokenization_pool.shutdown()
        if self._owns_ledger:
            self.cache_stats.close()

    def set_tokenizer(self, tokenizer: Tokenizer, model_name: str) -> None:
        self.tokenization_pool.set_tokenizer(tokenizer, model_name)

    def set_capture(self, capture_recorder) -> None:
        """Attach/detach the input flight recorder after construction
        (obs/capture.py).  Racy-benign: scoring threads read the
        attribute once per request."""
        self.capture = capture_recorder

    def _capture_score(
        self,
        model_name: str,
        tokens: Sequence[int],
        pod_identifiers: Optional[Sequence[str]],
        scores: Dict[str, float],
    ) -> None:
        """Capture must never fail a scoring request (same contract
        as the analytics ledger).  Scores are copied — the caller owns
        the returned dict and may mutate it."""
        try:
            self.capture.record_score(
                model_name, tokens, pod_identifiers, dict(scores)
            )
        except Exception:  # noqa: BLE001 - scoring outlives capture bugs
            logger.exception("input capture record failed")

    def set_policy_engine(self, policy_engine) -> None:
        """Attach a tiering PolicyEngine after construction (binds the
        indexer's ledger to its feed)."""
        self.policy_engine = policy_engine
        if policy_engine is None:
            return
        if self.cache_stats is not None:
            policy_engine.bind_ledger(self.cache_stats)
        else:
            # Dead configuration (e.g. TIERING=1 with CACHESTATS=0):
            # every scoring hook gates on the ledger, so the engine
            # would sit inert — zeros in /debug/tiering, LRU-only
            # eviction — with nothing explaining why.  Be loud once.
            logger.warning(
                "tiering PolicyEngine attached to an indexer without a "
                "cachestats ledger (CACHESTATS disabled?): the policy "
                "feed will learn nothing and predictive eviction "
                "degrades to LRU (docs/tiering.md)"
            )

    def set_transfer_engine(self, transfer_engine) -> None:
        """Attach a TransferEngine after construction (binds the
        indexer's ledger for hot-family ranking)."""
        self.transfer_engine = transfer_engine
        if transfer_engine is None:
            return
        if self.cache_stats is not None:
            transfer_engine.bind_ledger(self.cache_stats)
        else:
            # Same dead-configuration trap as tiering: without the
            # ledger the warm-up ranking has no reuse signal and falls
            # back to catalog insertion order.  Be loud once.
            logger.warning(
                "TransferEngine attached to an indexer without a "
                "cachestats ledger (CACHESTATS disabled?): warm-up "
                "family ranking degrades to catalog order "
                "(docs/transfer.md)"
            )

    def _fill_filtered_zero(
        self,
        scores: Dict[str, float],
        pod_identifiers: Optional[Sequence[str]],
    ) -> Dict[str, float]:
        """Unknown-pod filter fix-up: pods named in the request filter
        but absent from the index get an explicit 0.0 entry (not a
        silently missing key) so planner, ledger, and explain agree on
        the candidate set.  Mutates and returns ``scores`` (fresh per
        request in every lane)."""
        if pod_identifiers:
            for pod in pod_identifiers:
                scores.setdefault(pod, 0.0)
        return scores

    def _blend_loads(
        self,
        scores: Dict[str, float],
        pod_loads: Optional[Dict[str, float]],
    ) -> Dict[str, float]:
        """Fold per-pod queue depth into scores: ``score / (1 + blend
        * depth)``.  With no loads or a zero coefficient the INPUT
        dict is returned unchanged — planner-off parity stays
        bit-identical to the unblended path."""
        blend = self._load_blend
        if not pod_loads or blend <= 0.0:
            return scores
        return {
            pod: score
            / (1.0 + blend * max(0.0, float(pod_loads.get(pod, 0.0))))
            for pod, score in scores.items()
        }

    def _tokens_and_block_keys(
        self,
        prompt: str,
        model_name: str,
        render_req: Optional[ApplyChatTemplateRequest],
    ) -> Tuple[List[int], List[int]]:
        """Straight-line front half of the read path: prompt -> tokens
        -> chained block keys, with per-stage spans when a trace is
        active (the tokenization pool adds its own sub-spans under
        "tokenize").  Used by the explain surface and by
        ``get_pod_scores`` when the fast lane is disabled."""
        with obs_span("tokenize") as s:
            tokens = self.tokenization_pool.tokenize(
                prompt, model_name, render_req
            )
            s.set_attr("tokens", len(tokens))
        trace(logger, "tokenized prompt to %d tokens", len(tokens))

        with obs_span("hash_blocks") as s:
            block_keys = self.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, model_name
            )
            s.set_attr("block_keys", len(block_keys))
        trace(logger, "derived %d block keys", len(block_keys))
        return tokens, block_keys

    def get_pod_scores(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
        pod_loads: Optional[Dict[str, float]] = None,
    ) -> Dict[str, float]:
        """Score candidate pods for a prompt.

        ``pod_identifiers`` filters the result; None/empty scores every pod
        the index knows about.  Filtered pods unknown to the index get
        explicit 0.0 entries.  ``pod_loads`` (optional per-pod queue
        depths) blends load into the result when the ``LOAD_BLEND``
        coefficient is set; omitted, scores are bit-identical to the
        load-blind path.
        """
        if self._fast_lane:
            scores = self._get_pod_scores_fast(
                prompt, model_name, pod_identifiers, render_req
            )
        else:
            scores = self._get_pod_scores_straight(
                prompt, model_name, pod_identifiers, render_req
            )
        return self._blend_loads(scores, pod_loads)

    def _get_pod_scores_straight(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
    ) -> Dict[str, float]:
        """The pre-fast-lane path: hash every block, one lookup, one
        scoring pass (the same ``begin``/``advance`` drive ``score()``
        wraps, unrolled here so the chain's attribution state is
        readable).  Kept as the parity oracle (READ_PATH_FAST_LANE=0)
        and the fallback when the fast lane is configured off."""
        tokens, block_keys = self._tokens_and_block_keys(
            prompt, model_name, render_req
        )
        if not block_keys:
            if self.capture is not None:
                self._capture_score(model_name, tokens, pod_identifiers, {})
            return {}

        ledger = self.cache_stats
        sampled = ledger is not None and ledger.should_sample()
        track_tiers = sampled and ledger.tier_detail_due()
        traced = current_trace() is not None
        pod_set = set(pod_identifiers) if pod_identifiers else None
        with obs_span("index_lookup") as s:
            key_to_pods = self.kv_block_index.lookup(block_keys, pod_set)
            s.set_attr("keys_hit", len(key_to_pods))
        with obs_span("score") as s:
            chain = self.scorer.begin(
                track_tiers=track_tiers, track_deaths=traced
            )
            # lookup() already applied the pod filter; feeding every
            # key keeps break indices aligned with explain's.
            self.scorer.advance(
                chain, [key_to_pods.get(key, ()) for key in block_keys]
            )
            scores = self._fill_filtered_zero(
                chain.scores, pod_identifiers
            )
            s.set_attr("pods", len(scores))
            if traced:
                s.set_attr("provenance", _provenance_attr(chain))
        if sampled:
            family = ledger.family_key(block_keys, len(block_keys))
            _ledger_record(
                ledger,
                family,
                model_name,
                len(block_keys),
                chain.matched_blocks,
                chain.tier_counts,
            )
            if self.policy_engine is not None:
                self.policy_engine.observe_scored(block_keys, family)
        if self.capture is not None:
            self._capture_score(model_name, tokens, pod_identifiers, scores)
        logger.debug(
            "scored %d pods over %d block keys", len(scores), len(block_keys)
        )
        return scores

    def _get_pod_scores_fast(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
    ) -> Dict[str, float]:
        """The fast lane: memoized prefix keys + chunked early-exit
        hashing/lookup/scoring, fronted by the request score memo.
        Identical scores to the straight path
        (tests/test_read_path_fastlane.py pins it)."""
        memo = self._score_memo
        memo_key = None
        if memo is not None and render_req is None:
            memo_key = (
                prompt,
                model_name,
                tuple(pod_identifiers) if pod_identifiers else None,
            )
        active_trace = current_trace()
        ledger = self.cache_stats
        sampled = ledger is not None and ledger.should_sample()
        track_tiers = sampled and ledger.tier_detail_due()
        with obs_span("tokenize") as s:
            result = self.tokenization_pool.tokenize_with_keys(
                prompt, model_name, render_req, self._key_space
            )
            s.set_attr("tokens", len(result.tokens))
        # Anchor for the traced stage layout below: everything from
        # here to the emit point belongs to some walk stage, so the
        # stage spans are laid out to cover this whole interval (the
        # slo smoke pins stage-sum ≈ end-to-end ±5%).
        walk_start = time.perf_counter()

        tokens = result.tokens
        block_size = self.token_processor.block_size
        total_blocks = len(tokens) // block_size
        if total_blocks == 0:
            if self.capture is not None:
                self._capture_score(model_name, tokens, pod_identifiers, {})
            return {}

        memo_keys = result.memo_keys
        memo_blocks = min(len(memo_keys), total_blocks)
        pod_set = set(pod_identifiers) if pod_identifiers else None

        index = self.kv_block_index
        # Chain-speculation depth signal (docs/replication.md): blocks
        # the last walk of this exact prompt matched, harvested from a
        # stale memo entry below — a multi-turn family whose prefix
        # stayed deep predicts a likely-alive chain worth dispatching
        # ahead of the current chunk's replies.
        predicted_hit_blocks = 0
        if memo_key is not None and active_trace is None:
            # Exact-prompt score memo, validated optimistically: the
            # memoized result is served only when (1) tokenization
            # served the exact token stream the walk that computed it
            # saw (count alone is not enough — an overlapping prompt's
            # add_tokenization can re-split a shared prefix-store chunk
            # to different token values with the same count, and
            # different tokens mean different block keys) and (2) the
            # index's per-shard version vector is unchanged since that
            # walk began (no score-relevant mutation landed).  Traced
            # requests always walk, so sampled traces carry real stage
            # spans.
            hit = memo.get(memo_key)
            if (
                hit is not None
                and len(hit.tokens) == len(tokens)
                and hit.version == index.version_vector()
                and list(hit.tokens) == tokens
            ):
                index.touch_chain(hit.touch_keys)
                if self._record_chain_lookup is not None:
                    self._record_chain_lookup(0.0, hit.max_pod_hits)
                if sampled:
                    # Replay the elided walk's attribution so the
                    # ledger's view is hit-path-independent (pinned by
                    # the memo≡walk ledger test).
                    _ledger_record(
                        ledger,
                        hit.family,
                        model_name,
                        total_blocks,
                        hit.matched_blocks,
                        hit.tier_counts,
                    )
                    if self.policy_engine is not None:
                        # The elided walk's chain keys are the touched
                        # resident ones; the rhythm update rides them.
                        self.policy_engine.observe_scored(
                            hit.touch_keys, hit.family
                        )
                if self.capture is not None:
                    # The memo's tokens ARE the served stream (the
                    # validator just proved it) — no copy needed.
                    self._capture_score(
                        model_name, hit.tokens, pod_identifiers,
                        hit.scores,
                    )
                logger.debug(
                    "score-memo hit: %d pods over %d chain keys",
                    len(hit.scores),
                    len(hit.touch_keys),
                )
                return dict(hit.scores)
            if hit is not None:
                predicted_hit_blocks = hit.matched_blocks
        processor = self.token_processor
        scorer = self.scorer
        chain = scorer.begin(
            track_tiers=track_tiers, track_deaths=active_trace is not None
        )
        chunk_size = self._lookup_chunk
        perf = time.perf_counter

        hash_s = 0.0
        lookup_s = 0.0
        score_s = 0.0
        keys_hit = 0
        record_lookup = self._record_chain_lookup
        hits_per_pod: Dict[str, int] = {}
        parent_key = (
            memo_keys[memo_blocks - 1] if memo_blocks else EMPTY_BLOCK_HASH
        )
        keys_done: List[int] = []
        touched_keys: List[int] = []
        # Captured BEFORE the first lookup: a mutation landing anywhere
        # during the walk bumps past this vector, so the memoized result
        # can never validate against post-mutation state.
        memo_version = (
            index.version_vector() if memo_key is not None else None
        )
        position = 0  # blocks consumed (scored)
        next_pos = 0  # blocks hashed + dispatched (>= position)
        alive = True

        def next_chunk() -> Sequence[int]:
            """Hash (or slice from the prefix memo) the next
            un-dispatched chunk, advancing the dispatch cursor.  Both
            drives below share it, so chunk boundaries — hence scorer
            advance granularity and scores — are identical."""
            nonlocal hash_s, next_pos, parent_key, chunk_size
            t_0 = perf()
            if next_pos < memo_blocks:
                # The memoized prefix needs no hashing, so early exit
                # saves nothing there: drive it as ONE chunk (one
                # grouped lock pass over the whole prefix).
                chunk: Sequence[int] = (
                    memo_keys[:memo_blocks]
                    if next_pos == 0 and memo_blocks == len(memo_keys)
                    else memo_keys[next_pos:memo_blocks]
                )
            else:
                n_blocks = min(chunk_size, total_blocks - next_pos)
                suffix = tokens[
                    next_pos * block_size : (next_pos + n_blocks) * block_size
                ]
                chunk = processor.extend_block_keys(
                    parent_key, suffix, model_name
                )
                parent_key = chunk[-1] if chunk else parent_key
                # Hash chunks double up to the cap: early exit stays
                # fine-grained near the front of a cold chain (where
                # breaks live) while a long live suffix amortizes the
                # per-chunk overhead.
                if chunk_size < 512:
                    chunk_size *= 2
            hash_s += perf() - t_0
            next_pos += len(chunk)
            return chunk

        # Pipelined chunk drive (docs/replication.md): against a
        # backend whose lookup_chain_async runs the owner fan-out off
        # the calling thread (the cluster RemoteIndex), hash and
        # dispatch chunk N+1 while chunk N's replies are on the wire.
        # One chunk ahead is unconditional; deeper dispatch is chain
        # speculation, gated on a likely-alive-deep prediction (the
        # prefix-memo depth, a stale memo entry's matched depth, or
        # the ledger's per-family average).  Results are consumed
        # strictly in chain order on this thread, so scores stay
        # bit-identical to the sequential drive — early exit just
        # drops the speculative in-flight results on the floor.
        depth = (
            self._pipeline_depth
            if callable(getattr(index, "lookup_chain_async", None))
            else 0
        )
        in_flight: deque = deque()
        speculated = 0
        predicted_blocks = max(memo_blocks, predicted_hit_blocks)
        ledger_predicted = ledger is None
        while position < total_blocks and alive:
            if depth > 0:
                while len(in_flight) < depth and next_pos < total_blocks:
                    if len(in_flight) >= 2 and not (
                        self._speculate and next_pos < predicted_blocks
                    ):
                        break
                    if in_flight:
                        speculated += 1
                    chunk = next_chunk()
                    # Dispatch counts as lookup time: an unarmed (or
                    # closed) router resolves the chunk inline right
                    # here, and that wall time must land in the
                    # index_lookup stage, not in an untracked gap
                    # (the slo smoke pins stage-sum ≈ end-to-end).
                    t_d = perf()
                    handle = index.lookup_chain_async(chunk)
                    lookup_s += perf() - t_d
                    in_flight.append((chunk, handle))
                key_chunk, handle = in_flight.popleft()
                t_1 = perf()
                pods_per_key = handle.result()
            else:
                key_chunk = next_chunk()
                t_1 = perf()
                pods_per_key = index.lookup_chain(key_chunk)
            t_2 = perf()
            lookup_s += t_2 - t_1
            keys_done.extend(key_chunk)
            keys_hit += len(pods_per_key)
            if memo_key is not None and pods_per_key:
                touched_keys.extend(key_chunk[: len(pods_per_key)])
            if record_lookup is not None:
                # Tally over the FILTERED view (what the straight
                # path's instrumented lookup counts): a non-candidate
                # pod's residency must not move the hit metrics.  One
                # knowing divergence: the tally covers only the chain
                # actually driven, so residency past the point where
                # the chain died for every candidate (which early exit
                # never looks up, and which cannot move any score) is
                # not counted, while the straight path's full lookup
                # would count it (docs/performance.md).
                for pods in pods_per_key:
                    for entry in pods:
                        pod_id = entry.pod_identifier
                        if pod_set is not None and pod_id not in pod_set:
                            continue
                        hits_per_pod[pod_id] = (
                            hits_per_pod.get(pod_id, 0) + 1
                        )
            alive = (
                scorer.advance(chain, pods_per_key, pod_set)
                and len(pods_per_key) == len(key_chunk)
            )
            score_s += perf() - t_2
            position += len(key_chunk)
            if (
                not ledger_predicted
                and depth > 1
                and self._speculate
                and len(keys_done)
                >= min(ledger.config.family_blocks, total_blocks)
            ):
                # One mid-walk refinement: once enough of the chain is
                # hashed to derive the family id, the ledger's average
                # matched depth for it extends the speculation horizon
                # (multi-turn families that historically match deep).
                ledger_predicted = True
                prediction = ledger.predicted_matched_blocks(
                    ledger.family_key(keys_done, total_blocks)
                )
                if prediction is not None:
                    predicted_blocks = max(
                        predicted_blocks, int(prediction)
                    )
        if speculated or in_flight:
            # Wasted = dispatched but never consumed (early exit after
            # the chain died); the executor finishes them harmlessly in
            # the background and their keys never reach keys_done, the
            # prefix store, or the family id.
            record_speculation = getattr(index, "record_speculation", None)
            if callable(record_speculation):
                record_speculation(speculated, len(in_flight))

        if (
            self._key_space is not None
            and len(keys_done) > memo_blocks
            and result.text
        ):
            # New keys were hashed: memoize them on the prompt's chunk
            # chain so the next request over this prefix resumes
            # instead of re-hashing (advisory; evictions only cost a
            # re-hash).  min_blocks skips re-writing the records the
            # memo was resumed from — only the new suffix's chunks pay.
            self.prefix_store.attach_block_keys(
                result.text,
                model_name,
                self._key_space,
                keys_done,
                tokens,
                min_blocks=memo_blocks,
            )

        max_pod_hits = max(hits_per_pod.values()) if hits_per_pod else 0
        if record_lookup is not None:
            record_lookup(lookup_s, max_pod_hits)

        if chain.deaths is not None and chain.active:
            # The chain died by lookup truncation (the next key had no
            # resident pods) rather than by scorer intersection; the
            # surviving pods' break index is the first un-looked-up
            # block — exactly where explain's full walk would break
            # them (pinned by the provenance≡explain test).
            if not alive:
                for pod in chain.active:
                    chain.deaths.setdefault(pod, chain.position)

        # Filter fix-up BEFORE the memo store: memo keys include the
        # pod-filter tuple, so memoized entries carry the filled dict a
        # re-walk under the same filter would produce.
        self._fill_filtered_zero(chain.scores, pod_identifiers)

        family = None
        if ledger is not None and (sampled or memo_key is not None):
            # The family id must be lane- and memo-state-independent
            # (one prompt, one family): an early exit can leave
            # keys_done short of family_blocks (e.g. a dead 2-block
            # memoized prefix), so hash the few missing prefix blocks
            # before deriving it — bounded by family_blocks, and only
            # on walks that died inside the family prefix.
            need = min(ledger.config.family_blocks, total_blocks)
            if len(keys_done) < need:
                keys_done.extend(
                    processor.extend_block_keys(
                        keys_done[-1],
                        tokens[
                            len(keys_done) * block_size: need * block_size
                        ],
                        model_name,
                    )
                )
            family = ledger.family_key(keys_done, total_blocks)
        if memo_key is not None:
            memo.put(
                memo_key,
                _ScoreMemoEntry(
                    dict(chain.scores),
                    memo_version,
                    tuple(tokens),
                    tuple(touched_keys),
                    max_pod_hits,
                    family=family,
                    matched_blocks=chain.matched_blocks,
                    tier_counts=(
                        dict(chain.tier_counts)
                        if chain.tier_counts is not None
                        else None
                    ),
                ),
            )
        if sampled:
            _ledger_record(
                ledger,
                family,
                model_name,
                total_blocks,
                chain.matched_blocks,
                chain.tier_counts,
            )
            if self.policy_engine is not None:
                self.policy_engine.observe_scored(keys_done, family)

        tracer = active_trace
        if tracer is not None:
            # One span per pipeline stage (the stage vocabulary the
            # metrics histogram and the debug surface share), durations
            # accumulated across chunks and emitted as contiguous
            # intervals covering [walk_start, now].  lookup/score keep
            # their measured durations; hash_blocks absorbs the walk's
            # fixed bookkeeping (memo check + version capture up front,
            # memo store / ledger / prefix attach at the tail) so the
            # stage sum tracks the request's end-to-end latency.
            end = perf()
            span = tracer.add_completed(
                "hash_blocks", walk_start,
                end - lookup_s - score_s,
            )
            span.set_attr("block_keys", len(keys_done))
            span.set_attr("memo_blocks", memo_blocks)
            span = tracer.add_completed(
                "index_lookup", end - lookup_s - score_s, end - score_s
            )
            span.set_attr("keys_hit", keys_hit)
            span = tracer.add_completed("score", end - score_s, end)
            span.set_attr("pods", len(chain.scores))
            span.set_attr("provenance", _provenance_attr(chain))
        if self.capture is not None:
            self._capture_score(
                model_name, tokens, pod_identifiers, chain.scores
            )
        logger.debug(
            "fast-lane scored %d pods over %d/%d block keys "
            "(%d memoized)",
            len(chain.scores),
            len(keys_done),
            total_blocks,
            memo_blocks,
        )
        return chain.scores

    def get_pod_scores_planned(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        pod_loads: Optional[Dict[str, float]] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
    ) -> Tuple[Dict[str, float], Optional[Dict]]:
        """The opt-in planned scoring variant: ``get_pod_scores`` plus
        a transfer directive when an attached TransferEngine decides
        the best holder is overloaded and moving its blocks beats
        recompute (docs/transfer.md).  Returns ``(scores,
        directive_or_None)``; rides the explained walk because the
        planner needs the per-pod provenance, so it shares explain's
        cost profile — for schedulers that opted in, not the hot path.
        """
        scores, explanation = self.get_pod_scores_explained(
            prompt,
            model_name,
            pod_identifiers,
            render_req,
            pod_loads=pod_loads,
        )
        return scores, explanation.get("transfer")

    def get_pod_scores_explained(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
        pod_loads: Optional[Dict[str, float]] = None,
    ) -> Tuple[Dict[str, float], Dict]:
        """``get_pod_scores`` plus a per-pod score explanation.

        Returns ``(scores, explanation)``; scores are identical to
        ``get_pod_scores``.  The explanation carries token/block-key
        counts and, per pod, blocks matched, the block index where the
        consecutive-prefix chain broke, and per-tier hit counts (see
        ``LongestPrefixScorer.explain``); with ``pod_loads`` and an
        attached TransferEngine it also carries the load blend and the
        transfer planner's decision.  The debug surface — slower
        than the hot path by the explain bookkeeping (and it always
        walks the full chain: break indices need the straight-line
        path, never the early-exit fast lane); not for every request.
        """
        tokens, block_keys = self._tokens_and_block_keys(
            prompt, model_name, render_req
        )
        explanation: Dict = {
            "tokens": len(tokens),
            "block_keys": len(block_keys),
            "pods": {},
        }
        if not block_keys:
            if self.capture is not None:
                self._capture_score(model_name, tokens, pod_identifiers, {})
            return {}, explanation

        pod_set = set(pod_identifiers) if pod_identifiers else None
        with obs_span("index_lookup") as s:
            key_to_pods = self.kv_block_index.lookup(block_keys, pod_set)
            s.set_attr("keys_hit", len(key_to_pods))
        with obs_span("score") as s:
            per_pod = self.scorer.explain(block_keys, key_to_pods)
            s.set_attr("pods", len(per_pod))
            s.set_attr(
                "provenance",
                {
                    pod: {
                        "blocks_matched": detail["blocks_matched"],
                        "break_index": detail["break_index"],
                    }
                    for pod, detail in per_pod.items()
                },
            )
        if pod_identifiers:
            # Unknown-pod filter fix-up, explain flavor: explicit
            # zero-provenance entries so the planner, the ledger, and
            # this surface agree on the candidate set.
            for pod in pod_identifiers:
                per_pod.setdefault(
                    pod,
                    {
                        "score": 0.0,
                        "blocks_matched": 0,
                        "break_index": 0,
                        "tiers": {},
                    },
                )
        explanation["pods"] = per_pod
        scores = {pod: detail["score"] for pod, detail in per_pod.items()}
        if self.capture is not None:
            # Explain requests are scoring requests too: the replay
            # harness re-drives them through the plain scoring path
            # (scores are identical by the explain≡score property).
            self._capture_score(model_name, tokens, pod_identifiers, scores)
        ledger = self.cache_stats
        if ledger is not None and ledger.should_sample():
            # Explain requests are scoring requests too.  Attribution
            # comes from the same ScoreChain drive the hot path uses
            # (per-block best-resident-tier split, tier-sample gate
            # included) — recording the best pod's OWN tiers here
            # would feed the ledger a different split than the walk
            # records for the identical request.
            chain = self.scorer.begin(
                track_tiers=ledger.tier_detail_due()
            )
            self.scorer.advance(
                chain, [key_to_pods.get(key, ()) for key in block_keys]
            )
            family = ledger.family_key(block_keys, len(block_keys))
            _ledger_record(
                ledger,
                family,
                model_name,
                len(block_keys),
                chain.matched_blocks,
                chain.tier_counts,
            )
            if self.policy_engine is not None:
                self.policy_engine.observe_scored(block_keys, family)
        engine = self.policy_engine
        if engine is not None and per_pod:
            # Compute-or-load advice for the best pod's resident prefix
            # (docs/tiering.md): would loading its offloaded KV beat
            # recomputing it, or should the two overlap?  Advisory —
            # failures never fail an explain request.
            try:
                best_pod, best = max(
                    per_pod.items(), key=lambda item: item[1]["score"]
                )
                tiers = best.get("tiers") or {}
                tier = (
                    max(tiers.items(), key=lambda item: item[1])[0]
                    if tiers
                    else None
                )
                advice = engine.advisor.advise(
                    best["blocks_matched"], tier=tier
                )
                explanation["tiering"] = dict(
                    advice.to_dict(), pod=best_pod
                )
            except Exception:  # noqa: BLE001 — advice is advisory
                logger.exception("tiering advice failed")
        transfer = self.transfer_engine
        if transfer is not None and per_pod:
            # Transfer planning rides the RAW provenance (holders are
            # holders regardless of their queue); plan_for_chain never
            # raises into scoring (transfer/engine.py contract).
            directive = transfer.plan_for_chain(
                per_pod,
                pod_loads,
                block_keys,
                token_ids=tokens,
                block_size=getattr(
                    self.token_processor, "block_size", 16
                ),
            )
            if directive is not None:
                explanation["transfer"] = directive
        if pod_loads and self._load_blend > 0.0:
            blended = self._blend_loads(scores, pod_loads)
            explanation["load_blend"] = {
                "coefficient": self._load_blend,
                "pods": {
                    pod: {
                        "raw": scores[pod],
                        "queue_depth": float(
                            pod_loads.get(pod, 0.0)
                        ),
                        "blended": blended[pod],
                    }
                    for pod in sorted(scores)
                },
            }
            scores = blended
        return scores, explanation
