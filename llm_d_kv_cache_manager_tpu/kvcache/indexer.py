"""The Indexer: orchestration of the scoring read path.

``get_pod_scores(prompt, model, pods)`` answers the scheduler's question —
*which pod holds the longest consecutive prefix of this prompt's KV
blocks?* — by composing the subsystem stack (reference:
pkg/kvcache/indexer.go:124-165):

    tokenize (pool + prefix store [+ chat render])
      -> token chain -> request block keys (ChunkedTokenDatabase)
      -> index lookup (pluggable backend)
      -> longest-prefix tier-weighted score

One ``Config`` composes every module's config with defaults, so embedding
applications construct the whole stack from a single literal.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    Index,
    IndexConfig,
    new_index,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessor,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.scorer import (
    LongestPrefixScorer,
    ScorerConfig,
    new_scorer,
)
from llm_d_kv_cache_manager_tpu.obs.trace import span as obs_span
from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (
    ApplyChatTemplateRequest,
    ChatTemplatingProcessor,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (
    LRUStoreConfig,
    LRUTokenStore,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    CompositeTokenizer,
    LocalFastTokenizer,
    Tokenizer,
    TransformersTokenizer,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger, trace

logger = get_logger("kvcache.indexer")


@dataclass
class IndexerConfig:
    prefix_store_config: LRUStoreConfig = field(default_factory=LRUStoreConfig)
    token_processor_config: TokenProcessorConfig = field(
        default_factory=TokenProcessorConfig
    )
    kvblock_index_config: IndexConfig = field(default_factory=IndexConfig)
    scorer_config: ScorerConfig = field(default_factory=ScorerConfig)
    tokenizers_pool_config: TokenizationPoolConfig = field(
        default_factory=TokenizationPoolConfig
    )
    # Directory searched by the local tokenizer backend; None disables it.
    local_tokenizers_dir: Optional[str] = None
    # UDS path of a tokenizer sidecar (services/uds_tokenizer); None
    # disables that backend.  Composite order mirrors the reference's
    # local -> uds -> hf fallback chain (pkg/tokenization/pool.go:97-145).
    uds_tokenizer_path: Optional[str] = None


class Indexer:
    """Composes the read-path stack; see module docstring."""

    def __init__(
        self,
        config: Optional[IndexerConfig] = None,
        token_processor: Optional[TokenProcessor] = None,
        tokenizer: Optional[Tokenizer] = None,
        chat_processor: Optional[ChatTemplatingProcessor] = None,
    ) -> None:
        self.config = config or IndexerConfig()
        self.token_processor = token_processor or ChunkedTokenDatabase(
            self.config.token_processor_config
        )
        self.kv_block_index: Index = new_index(
            self.config.kvblock_index_config
        )
        self.scorer: LongestPrefixScorer = new_scorer(
            self.config.scorer_config
        )
        self.prefix_store = LRUTokenStore(self.config.prefix_store_config)
        self.chat_processor = chat_processor or ChatTemplatingProcessor()

        if tokenizer is None:
            backends: List[Tokenizer] = []
            if self.config.local_tokenizers_dir:
                backends.append(
                    LocalFastTokenizer(self.config.local_tokenizers_dir)
                )
            if self.config.uds_tokenizer_path:
                from llm_d_kv_cache_manager_tpu.tokenization.uds_tokenizer import (  # noqa: E501 - lazy: grpc only when configured
                    UdsTokenizer,
                )

                backends.append(UdsTokenizer(self.config.uds_tokenizer_path))
            backends.append(TransformersTokenizer())
            tokenizer = CompositeTokenizer(backends)
        self.tokenization_pool = TokenizationPool(
            tokenizer,
            self.prefix_store,
            self.config.tokenizers_pool_config,
            chat_processor=self.chat_processor,
        )

    def run(self) -> None:
        """Start background workers (idempotent)."""
        self.tokenization_pool.start()

    def shutdown(self) -> None:
        self.tokenization_pool.shutdown()

    def set_tokenizer(self, tokenizer: Tokenizer, model_name: str) -> None:
        self.tokenization_pool.set_tokenizer(tokenizer, model_name)

    def _tokens_and_block_keys(
        self,
        prompt: str,
        model_name: str,
        render_req: Optional[ApplyChatTemplateRequest],
    ) -> Tuple[List[int], List[int]]:
        """Shared front half of the read path: prompt -> tokens -> chained
        block keys, with per-stage spans when a trace is active (the
        tokenization pool adds its own sub-spans under "tokenize")."""
        with obs_span("tokenize") as s:
            tokens = self.tokenization_pool.tokenize(
                prompt, model_name, render_req
            )
            s.set_attr("tokens", len(tokens))
        trace(logger, "tokenized prompt to %d tokens", len(tokens))

        with obs_span("hash_blocks") as s:
            block_keys = self.token_processor.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, model_name
            )
            s.set_attr("block_keys", len(block_keys))
        trace(logger, "derived %d block keys", len(block_keys))
        return tokens, block_keys

    def get_pod_scores(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
    ) -> Dict[str, float]:
        """Score candidate pods for a prompt.

        ``pod_identifiers`` filters the result; None/empty scores every pod
        the index knows about.
        """
        _, block_keys = self._tokens_and_block_keys(
            prompt, model_name, render_req
        )
        if not block_keys:
            return {}

        pod_set = set(pod_identifiers) if pod_identifiers else None
        with obs_span("index_lookup") as s:
            key_to_pods = self.kv_block_index.lookup(block_keys, pod_set)
            s.set_attr("keys_hit", len(key_to_pods))
        with obs_span("score") as s:
            scores = self.scorer.score(block_keys, key_to_pods)
            s.set_attr("pods", len(scores))
        logger.debug(
            "scored %d pods over %d block keys", len(scores), len(block_keys)
        )
        return scores

    def get_pod_scores_explained(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        render_req: Optional[ApplyChatTemplateRequest] = None,
    ) -> Tuple[Dict[str, float], Dict]:
        """``get_pod_scores`` plus a per-pod score explanation.

        Returns ``(scores, explanation)``; scores are identical to
        ``get_pod_scores``.  The explanation carries token/block-key
        counts and, per pod, blocks matched, the block index where the
        consecutive-prefix chain broke, and per-tier hit counts (see
        ``LongestPrefixScorer.explain``).  The debug surface — slower
        than the hot path by the explain bookkeeping; not for every
        request.
        """
        tokens, block_keys = self._tokens_and_block_keys(
            prompt, model_name, render_req
        )
        explanation: Dict = {
            "tokens": len(tokens),
            "block_keys": len(block_keys),
            "pods": {},
        }
        if not block_keys:
            return {}, explanation

        pod_set = set(pod_identifiers) if pod_identifiers else None
        with obs_span("index_lookup") as s:
            key_to_pods = self.kv_block_index.lookup(block_keys, pod_set)
            s.set_attr("keys_hit", len(key_to_pods))
        with obs_span("score") as s:
            per_pod = self.scorer.explain(block_keys, key_to_pods)
            s.set_attr("pods", len(per_pod))
        explanation["pods"] = per_pod
        scores = {pod: detail["score"] for pod, detail in per_pod.items()}
        return scores, explanation
