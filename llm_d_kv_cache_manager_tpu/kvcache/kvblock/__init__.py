from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (  # noqa: F401
    EMPTY_BLOCK_HASH,
    Index,
    IndexConfig,
    PodEntry,
    new_index,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: F401
    ChunkedTokenDatabase,
    TokenProcessor,
    TokenProcessorConfig,
)
