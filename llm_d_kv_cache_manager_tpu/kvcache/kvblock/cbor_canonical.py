"""Minimal canonical (deterministic) CBOR encoder for block-hash payloads.

The cross-fleet block-hash contract requires bit-exact agreement with the
reference indexer, which hashes ``FNV-64a(CBOR-canonical([parent, tokens,
extra]))`` per chunk (reference: pkg/kvcache/kvblock/token_processor.go:94-112
using fxamacker/cbor CanonicalEncOptions).  Only the types that can appear in
that payload are supported: unsigned/negative integers, byte strings, text
strings, lists, booleans and null.  Canonical form here means RFC 8949 §4.2.1
core deterministic encoding: shortest-form integer heads, definite lengths.

A nil Go slice encodes as CBOR null (fxamacker default NilContainers mode);
callers express that by passing ``None`` rather than ``[]``.
"""

from __future__ import annotations

import struct
from typing import Any, Sequence

_UINT64_MAX = 0xFFFFFFFFFFFFFFFF


def _head(major: int, value: int) -> bytes:
    """Encode a major type + shortest-form unsigned argument."""
    mt = major << 5
    if value < 24:
        return bytes((mt | value,))
    if value < 0x100:
        return bytes((mt | 24, value))
    if value < 0x10000:
        return struct.pack(">BH", mt | 25, value)
    if value < 0x100000000:
        return struct.pack(">BI", mt | 26, value)
    if value <= _UINT64_MAX:
        return struct.pack(">BQ", mt | 27, value)
    raise ValueError(f"integer too large for CBOR head: {value}")


def _encode_into(item: Any, out: bytearray) -> None:
    if item is None:
        out.append(0xF6)
    elif item is True:
        out.append(0xF5)
    elif item is False:
        out.append(0xF4)
    elif isinstance(item, int):
        if item >= 0:
            out += _head(0, item)
        else:
            out += _head(1, -1 - item)
    elif isinstance(item, bytes):
        out += _head(2, len(item))
        out += item
    elif isinstance(item, str):
        raw = item.encode("utf-8")
        out += _head(3, len(raw))
        out += raw
    elif isinstance(item, (list, tuple)):
        out += _head(4, len(item))
        for element in item:
            _encode_into(element, out)
    else:
        raise TypeError(f"unsupported CBOR type: {type(item)!r}")


def encode_canonical(item: Any) -> bytes:
    """Encode ``item`` as deterministic CBOR bytes."""
    out = bytearray()
    _encode_into(item, out)
    return bytes(out)


class CborDecodeError(ValueError):
    """Malformed or unsupported CBOR input."""


def _read_head(data: bytes, pos: int) -> tuple:
    """Decode one major-type head; returns (major, value, next_pos)."""
    if pos >= len(data):
        raise CborDecodeError("truncated CBOR head")
    initial = data[pos]
    major, info = initial >> 5, initial & 0x1F
    pos += 1
    if info < 24:
        return major, info, pos
    widths = {24: 1, 25: 2, 26: 4, 27: 8}
    width = widths.get(info)
    if width is None:
        raise CborDecodeError(f"unsupported CBOR head info {info}")
    if pos + width > len(data):
        raise CborDecodeError("truncated CBOR head argument")
    return (
        major,
        int.from_bytes(data[pos : pos + width], "big"),
        pos + width,
    )


def _decode_at(data: bytes, pos: int, depth: int = 0) -> tuple:
    if depth > 64:
        raise CborDecodeError("CBOR nesting too deep")
    if pos < len(data) and data[pos] in (0xF4, 0xF5, 0xF6):
        simple = {0xF4: False, 0xF5: True, 0xF6: None}[data[pos]]
        return simple, pos + 1
    major, value, pos = _read_head(data, pos)
    if major == 0:
        return value, pos
    if major == 1:
        return -1 - value, pos
    if major in (2, 3):
        if pos + value > len(data):
            raise CborDecodeError("truncated CBOR string body")
        raw = data[pos : pos + value]
        if major == 3:
            try:
                return raw.decode("utf-8"), pos + value
            except UnicodeDecodeError as exc:
                raise CborDecodeError(f"invalid UTF-8 text: {exc}") from exc
        return raw, pos + value
    if major == 4:
        out = []
        for _ in range(value):
            item, pos = _decode_at(data, pos, depth + 1)
            out.append(item)
        return out, pos
    raise CborDecodeError(f"unsupported CBOR major type {major}")


def decode_canonical(data: bytes) -> Any:
    """Decode bytes produced by :func:`encode_canonical` (same type
    subset: ints, byte/text strings, lists, booleans, null).  Raises
    :class:`CborDecodeError` on truncation, trailing garbage, or types
    outside the subset — a torn snapshot/journal record must fail
    loudly, never decode to a half-document."""
    item, pos = _decode_at(data, 0)
    if pos != len(data):
        raise CborDecodeError(
            f"{len(data) - pos} trailing bytes after CBOR item"
        )
    return item


def encode_hash_payload(
    parent: int, tokens: Sequence[int] | None, extra: Any
) -> bytes:
    """Encode the 3-element ``[parent, tokens, extra]`` block-hash payload."""
    out = bytearray()
    out += _head(4, 3)
    _encode_into(parent, out)
    if tokens is None:
        out.append(0xF6)
    else:
        out += _head(4, len(tokens))
        for token in tokens:
            out += _head(0, token)
    _encode_into(extra, out)
    return bytes(out)


# ---- chunk-payload fast path (the pure-Python hash hot loop) ----------
#
# Every link of a block-hash chain encodes ``[parent, chunk_tokens,
# null]`` where parent is a uint64 and the token-list length equals the
# configured block size — so the array head, the 9-byte parent head
# shape, the token-list head, and the trailing null are invariant
# framing that `encode_hash_payload` re-derived per chunk through
# generic dispatch.  `encode_chunk_payload` precomputes the invariant
# pieces and inlines shortest-form uint heads for the tokens; output is
# bit-identical to ``encode_hash_payload(parent, tokens, None)``
# (pinned by tests/test_read_path_fastlane.py against the generic
# encoder and the golden chain vectors).  It returns a ``bytearray`` so
# the caller can hash it without a defensive ``bytes`` copy.

_TOKENS_HEAD_CACHE: dict = {}


def _tokens_head(n: int) -> bytes:
    head = _TOKENS_HEAD_CACHE.get(n)
    if head is None:
        head = _head(4, n)
        _TOKENS_HEAD_CACHE[n] = head
    return head


def encode_chunk_payload(parent: int, tokens: Sequence[int]) -> bytearray:
    """``[parent, tokens, null]`` as canonical CBOR, framing precomputed."""
    out = bytearray(b"\x83")  # array(3), invariant
    if parent < 24:
        out.append(parent)
    else:
        out += _head(0, parent)
    out += _tokens_head(len(tokens))
    pack = struct.pack
    for token in tokens:
        if token < 24:
            out.append(token)
        elif token < 0x100:
            out.append(0x18)
            out.append(token)
        elif token < 0x10000:
            out += pack(">BH", 0x19, token)
        elif token < 0x100000000:
            out += pack(">BI", 0x1A, token)
        else:
            out += _head(0, token)
    out.append(0xF6)  # null extra, invariant
    return out
