"""Minimal canonical (deterministic) CBOR encoder for block-hash payloads.

The cross-fleet block-hash contract requires bit-exact agreement with the
reference indexer, which hashes ``FNV-64a(CBOR-canonical([parent, tokens,
extra]))`` per chunk (reference: pkg/kvcache/kvblock/token_processor.go:94-112
using fxamacker/cbor CanonicalEncOptions).  Only the types that can appear in
that payload are supported: unsigned/negative integers, byte strings, text
strings, lists, booleans and null.  Canonical form here means RFC 8949 §4.2.1
core deterministic encoding: shortest-form integer heads, definite lengths.

A nil Go slice encodes as CBOR null (fxamacker default NilContainers mode);
callers express that by passing ``None`` rather than ``[]``.
"""

from __future__ import annotations

import struct
from typing import Any, Sequence

_UINT64_MAX = 0xFFFFFFFFFFFFFFFF


def _head(major: int, value: int) -> bytes:
    """Encode a major type + shortest-form unsigned argument."""
    mt = major << 5
    if value < 24:
        return bytes((mt | value,))
    if value < 0x100:
        return bytes((mt | 24, value))
    if value < 0x10000:
        return struct.pack(">BH", mt | 25, value)
    if value < 0x100000000:
        return struct.pack(">BI", mt | 26, value)
    if value <= _UINT64_MAX:
        return struct.pack(">BQ", mt | 27, value)
    raise ValueError(f"integer too large for CBOR head: {value}")


def _encode_into(item: Any, out: bytearray) -> None:
    if item is None:
        out.append(0xF6)
    elif item is True:
        out.append(0xF5)
    elif item is False:
        out.append(0xF4)
    elif isinstance(item, int):
        if item >= 0:
            out += _head(0, item)
        else:
            out += _head(1, -1 - item)
    elif isinstance(item, bytes):
        out += _head(2, len(item))
        out += item
    elif isinstance(item, str):
        raw = item.encode("utf-8")
        out += _head(3, len(raw))
        out += raw
    elif isinstance(item, (list, tuple)):
        out += _head(4, len(item))
        for element in item:
            _encode_into(element, out)
    else:
        raise TypeError(f"unsupported CBOR type: {type(item)!r}")


def encode_canonical(item: Any) -> bytes:
    """Encode ``item`` as deterministic CBOR bytes."""
    out = bytearray()
    _encode_into(item, out)
    return bytes(out)


def encode_hash_payload(
    parent: int, tokens: Sequence[int] | None, extra: Any
) -> bytes:
    """Encode the 3-element ``[parent, tokens, extra]`` block-hash payload."""
    out = bytearray()
    out += _head(4, 3)
    _encode_into(parent, out)
    if tokens is None:
        out.append(0xF6)
    else:
        out += _head(4, len(tokens))
        for token in tokens:
            out += _head(0, token)
    _encode_into(extra, out)
    return bytes(out)
