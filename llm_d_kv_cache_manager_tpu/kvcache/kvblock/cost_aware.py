"""Cost-aware (byte-budgeted) in-memory index.

Capability parity with the reference's ristretto-backed backend
(pkg/kvcache/kvblock/cost_aware_memory.go): instead of bounding the *count*
of keys, bound the approximate *bytes* resident, evicting
least-recently-used keys until under budget.  Default budget 2 GiB.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    CostAwareIndexConfig,
    Index,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder, victim
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("kvcache.cost_aware")

# Fixed per-entry overheads (dict slots, key ints, bookkeeping).  These are
# estimates in the same spirit as the reference's per-entry cost model
# (cost_aware_memory.go:125-157); exactness is not required, stability is.
_KEY_OVERHEAD = 64
_POD_ENTRY_OVERHEAD = 48


def _entry_cost(entry: PodEntry) -> int:
    return (
        _POD_ENTRY_OVERHEAD
        + len(entry.pod_identifier.encode())
        + len(entry.device_tier.encode())
    )


class CostAwareMemoryIndex(Index):
    def __init__(self, config: Optional[CostAwareIndexConfig] = None) -> None:
        self.config = config or CostAwareIndexConfig()
        # Leaf of the lock hierarchy: nothing else is acquired while
        # held (the watchdog asserts that under the storm tests).
        self._lock = lockorder.tracked(
            threading.Lock(), "CostAwareMemoryIndex._lock"
        )
        # request_key -> OrderedDict[PodEntry, cost]; outer dict is LRU.
        self._data: "OrderedDict[int, OrderedDict]" = OrderedDict()  # guarded-by: _lock
        self._engine_to_request: Dict[int, int] = {}  # guarded-by: _lock
        self._request_to_engines: Dict[int, Set[int]] = {}  # guarded-by: _lock
        self._cost = 0  # guarded-by: _lock

    @property
    def resident_cost_bytes(self) -> int:
        with self._lock:
            return self._cost

    def _evict_to_budget_locked(self) -> None:
        policy = self.config.eviction_policy
        while self._cost > self.config.max_cost_bytes and self._data:
            if policy is None:
                # The parity oracle: pristine pop-LRU-first, exactly
                # the pre-tiering eviction order (docs/tiering.md).
                key, pods = self._data.popitem(last=False)
            else:
                key = self._select_victim_locked(policy)
                pods = self._data.pop(key)
            self._cost -= _KEY_OVERHEAD + sum(pods.values())
            for engine_key in self._request_to_engines.pop(key, ()):  # type: ignore[arg-type]
                self._engine_to_request.pop(engine_key, None)

    def _select_victim_locked(self, policy) -> int:
        """Predictive victim selection over an LRU-ordered sample.

        The policy ranks ``(key, byte-cost)`` pairs against its own
        immutable snapshot (no locks taken under ours); the shared
        guard (utils/victim.py) bounds-checks the answer and falls
        back to the LRU-first victim on any policy failure."""
        sample = []
        limit = victim.sample_limit(policy)
        for key in self._data:  # insertion order == LRU order
            pods = self._data[key]
            sample.append((key, _KEY_OVERHEAD + sum(pods.values())))
            if len(sample) >= limit:
                break
        return sample[victim.guarded_select(policy, sample, logger)][0]

    def _admit_locked(
        self, request_key: int, entries: Sequence[PodEntry]
    ) -> None:
        """Shared admission path for add() and restore_entries():
        get-or-create the key's pod map, charge per-entry costs,
        refresh recency, and trim to pod_cache_size — the single place
        the cost accounting lives, so live adds and recovery restores
        can never drift apart."""
        pods = self._data.get(request_key)
        if pods is None:
            pods = OrderedDict()
            self._data[request_key] = pods
            self._cost += _KEY_OVERHEAD
        else:
            self._data.move_to_end(request_key)
        for entry in entries:
            if entry not in pods:
                cost = _entry_cost(entry)
                pods[entry] = cost
                self._cost += cost
            else:
                pods.move_to_end(entry)
        # Bound pods per key like the in-memory backend.
        while len(pods) > self.config.pod_cache_size:
            _, cost = pods.popitem(last=False)
            self._cost -= cost

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")
        result: Dict[int, List[PodEntry]] = {}
        with self._lock:
            for key in request_keys:
                pods = self._data.get(key)
                if pods is None:
                    continue
                self._data.move_to_end(key)
                if not pods:
                    return result
                selected = [
                    p
                    for p in pods
                    if not pod_identifier_set
                    or p.pod_identifier in pod_identifier_set
                ]
                if selected:
                    result[key] = selected
        return result

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for add")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")

        with self._lock:
            for engine_key, request_key in zip(engine_keys, request_keys):
                self._engine_to_request[engine_key] = request_key
                self._request_to_engines.setdefault(request_key, set()).add(
                    engine_key
                )
                self._admit_locked(request_key, entries)
            self._evict_to_budget_locked()

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction")
        with self._lock:
            request_key = self._engine_to_request.get(engine_key)
            if request_key is None:
                return
            pods = self._data.get(request_key)
            if pods is None:
                self._engine_to_request.pop(engine_key, None)
                return
            for entry in entries:
                cost = pods.pop(entry, None)
                if cost is not None:
                    self._cost -= cost
            if not pods:
                del self._data[request_key]
                self._cost -= _KEY_OVERHEAD
                for ek in self._request_to_engines.pop(request_key, ()):
                    self._engine_to_request.pop(ek, None)

    def get_request_key(self, engine_key: int) -> int:
        with self._lock:
            request_key = self._engine_to_request.get(engine_key)
        if request_key is None:
            raise KeyError(f"engine key not found: {engine_key:#x}")
        return request_key

    def dump_entries(
        self,
    ) -> Tuple[List[Tuple[int, List[PodEntry]]], List[Tuple[int, int]]]:
        with self._lock:
            block_entries = [
                (request_key, list(pods))
                for request_key, pods in self._data.items()
            ]
            engine_map = list(self._engine_to_request.items())
        return block_entries, engine_map

    def restore_entries(
        self,
        block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
        engine_map: Sequence[Tuple[int, int]],
    ) -> int:
        restored = 0
        with self._lock:
            for request_key, entries in block_entries:
                if not entries:
                    continue
                self._admit_locked(request_key, entries)
                restored += 1
            for engine_key, request_key in engine_map:
                self._engine_to_request[engine_key] = request_key
                self._request_to_engines.setdefault(request_key, set()).add(
                    engine_key
                )
            self._evict_to_budget_locked()
        return restored

    def purge_pod(self, pod_identifier: str) -> int:
        removed = 0
        with self._lock:
            for request_key in list(self._data):
                pods = self._data[request_key]
                victims = [
                    entry
                    for entry in pods
                    if entry.pod_identifier == pod_identifier
                ]
                for entry in victims:
                    self._cost -= pods.pop(entry)
                removed += len(victims)
                if not pods:
                    del self._data[request_key]
                    self._cost -= _KEY_OVERHEAD
                    for ek in self._request_to_engines.pop(
                        request_key, ()
                    ):
                        self._engine_to_request.pop(ek, None)
        return removed
