"""Default in-memory index: lock-striped shards of bounded LRU maps.

``request_key -> PodCache`` (an LRU of PodEntry) plus
``engine_key -> request_key`` for evictions, mirroring the reference's
two-level design (pkg/kvcache/kvblock/in_memory.go:105-270) with atomic
put-if-absent instead of Go's double-checked insert.

The request-key map is sharded N ways (power of two, key-masked): block
keys are FNV-64 outputs, so the low bits are uniformly distributed and a
bitmask spreads keys evenly.  Each shard is its own ``LRUCache`` with its
own lock, so concurrent scoring reads and kvevents applies touching
different shards never convoy on one lock (the pre-shard design
serialized every reader and the event writer behind a single map lock).
Capacity is budgeted per shard (``ceil(size / shards)``), which makes the
global bound approximate: eviction is LRU *within* a shard, the standard
striped-cache trade.  ``shards=1`` restores the exact single-LRU
semantics.

The engine->request map stays a single LRU: it is only touched by the
event write path (adds, evictions, parent resolution), never by scoring
reads, so it does not contend with the read path.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    Index,
    InMemoryIndexConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache

# The global acquisition order of this backend, declared once for both
# halves of KV006: the comments feed the static analyzer, the
# ``lockorder`` calls arm the runtime watchdog (asserted under the
# concurrency storms with KVTPU_LOCK_ORDER_DEBUG=1).  Shard stripes
# are LRUCache instances acquired in ascending shard-index order by
# every cross-shard operation below (never nested — the rank check is
# armed in case that ever changes).  A shard lock is never held across
# a pod-cache call, but a pod-cache lock IS held while its bounded
# ``entries`` LRU takes its own lock (add_all/snapshot/purge), so the
# pod-cache lock precedes LRUCache._lock globally.
# kvlint: lock-order: LRUCache._lock ascending
lockorder.declare_ascending("LRUCache._lock")
# kvlint: lock-order: _PodCache.lock < LRUCache._lock
lockorder.declare_order("_PodCache.lock", "LRUCache._lock")


class _PodCache:
    """Bounded recency set of PodEntry for one block key."""

    __slots__ = ("entries", "lock", "_snap")

    def __init__(self, capacity: int) -> None:
        self.entries: LRUCache[PodEntry, None] = LRUCache(capacity)
        self.lock = lockorder.tracked(
            threading.Lock(), "_PodCache.lock"
        )
        # Cached immutable snapshot of the entries, rebuilt lazily after
        # each mutation.  Read WITHOUT the lock by design: a reader
        # either sees a fully-built tuple published before the last
        # mutation (linearizes before it) or None (rebuilds under the
        # lock) — never a torn value, since tuple publication is a
        # single reference store.  This turns the steady-state scoring
        # read (hundreds of snapshots per request) into one attribute
        # load instead of a lock round-trip + list build per key.
        self._snap: Optional[Tuple[PodEntry, ...]] = None

    def add_all(self, entries: Sequence[PodEntry]) -> None:
        with self.lock:
            for entry in entries:
                self.entries.put(entry, None)
            self._snap = None

    def remove_all(self, entries: Sequence[PodEntry]) -> bool:
        """Remove entries; return True if the cache is now empty."""
        with self.lock:
            for entry in entries:
                self.entries.remove(entry)
            self._snap = None
            return len(self.entries) == 0

    def purge(self, pod_identifier: str) -> Tuple[int, bool]:
        """Drop every entry of one pod; returns (removed, now_empty)."""
        with self.lock:
            victims = [
                entry
                for entry in self.entries.keys()
                if entry.pod_identifier == pod_identifier
            ]
            for entry in victims:
                self.entries.remove(entry)
            if victims:
                self._snap = None
            return len(victims), len(self.entries) == 0

    def snapshot(self) -> Sequence[PodEntry]:
        # gil-atomic: single ref read; a stale None only costs a rebuild
        snap = self._snap
        if snap is None:
            with self.lock:
                snap = tuple(self.entries.keys())
                self._snap = snap
        return snap

    def __len__(self) -> int:
        return len(self.entries)


def _shard_count(requested: int) -> int:
    """Round the configured shard count up to a power of two (>= 1)."""
    if requested <= 1:
        return 1
    n = 1
    while n < requested:
        n <<= 1
    return n


class InMemoryIndex(Index):
    def __init__(self, config: Optional[InMemoryIndexConfig] = None) -> None:
        self.config = config or InMemoryIndexConfig()
        n_shards = _shard_count(self.config.shards)
        self._mask = n_shards - 1
        per_shard = max(1, -(-self.config.size // n_shards))
        self._shards: List[LRUCache[int, _PodCache]] = [
            LRUCache(per_shard, lock_rank=i) for i in range(n_shards)
        ]
        self._engine_to_request: LRUCache[int, int] = LRUCache(
            self.config.size
        )
        # Shard grouping memo for lookup_chain, keyed on key-TUPLE
        # identity: the fast lane re-presents the same memoized key
        # tuple request after request, and its shard grouping is a pure
        # function of the keys.  Entries hold a strong ref and are
        # validated with ``is`` (id() reuse can never alias); bounded
        # by wholesale clear; single-key dict ops only (benign under
        # the GIL).  Lists (fresh per request) are never cached.
        self._group_cache: Dict[int, tuple] = {}
        # Per-shard mutation counters backing the indexer's score memo
        # (docs/performance.md): every score-relevant mutation — entry
        # add/remove, capacity eviction, restore, purge — bumps its
        # shard AFTER the mutation is visible, so an optimistic reader
        # that captured the vector BEFORE its walk can never validate
        # a result the mutation invalidated.  Recency touches do not
        # bump (they change eviction order, not scores; the eviction
        # itself bumps when it happens).  Deliberately lock-free: a
        # racing ``+= 1`` pair can lose an increment, but counters only
        # ever advance, so a completed bump still always differs from
        # any vector captured before it — equality validation stays
        # sound — and a global lock here would re-serialize exactly the
        # reader/writer paths the shard striping de-convoys.
        self._versions: List[int] = [0] * n_shards

    _GROUP_CACHE_MAX = 1024

    # -- shard plumbing -------------------------------------------------

    def _shard(self, request_key: int) -> LRUCache[int, _PodCache]:
        return self._shards[request_key & self._mask]

    def _peek_ordered(
        self,
        request_keys: Sequence[int],
        groups: Optional[Dict[int, Tuple[List[int], List[int]]]] = None,
    ) -> List[Optional[_PodCache]]:
        """Per-key pod caches in input order, one lock round-trip per
        shard touched (not per key).  Pass precomputed ``groups`` (from
        ``_chain_groups``) to reuse one grouping for peek + touch."""
        if not self._mask:
            return self._shards[0].peek_many(request_keys)
        if groups is None:
            groups = self._chain_groups(request_keys)
        out: List[Optional[_PodCache]] = [None] * len(request_keys)
        # Ascending shard order here and in every other cross-shard
        # walk: the locks are taken sequentially today, so this is
        # deadlock-proofing by construction (KV006's ascending
        # declaration above holds even if a walk ever becomes
        # two-phase), at the cost of one tiny sort per call.
        for shard_index in sorted(groups):
            positions, keys = groups[shard_index]
            values = self._shards[shard_index].peek_many(keys)
            for i, value in zip(positions, values):
                out[i] = value
        return out

    def _chain_groups(
        self, request_keys: Sequence[int]
    ) -> Dict[int, Tuple[List[int], List[int]]]:
        """shard index -> (positions, keys) for one key sequence; the
        grouping is memoized for tuples (see ``_group_cache``)."""
        is_tuple = type(request_keys) is tuple
        if is_tuple:
            cached = self._group_cache.get(id(request_keys))
            if cached is not None and cached[0] is request_keys:
                return cached[1]
        groups: Dict[int, Tuple[List[int], List[int]]] = {}
        mask = self._mask
        for i, key in enumerate(request_keys):
            group = groups.get(key & mask)
            if group is None:
                group = groups[key & mask] = ([], [])
            group[0].append(i)
            group[1].append(key)
        if is_tuple:
            cache = self._group_cache
            if len(cache) >= self._GROUP_CACHE_MAX:
                cache.clear()
            # gil-atomic: single-key dict put; value is pure in the key
            cache[id(request_keys)] = (request_keys, groups)
        return groups

    def _bump_shards(self, shard_indices) -> None:
        """Advance the mutation version of each shard in
        ``shard_indices`` (duplicates allowed; called after the
        mutation is visible)."""
        versions = self._versions
        for shard_index in shard_indices:
            # gil-atomic: lone-advance counter; a lost ++ still differs
            # from every vector captured before this bump
            versions[shard_index] += 1

    def version_vector(self) -> Tuple[int, ...]:
        """Point-in-time per-shard mutation versions.  Equal vectors
        before and after an optimistic read prove no score-relevant
        mutation landed in between (the indexer's score-memo
        validation; see docs/performance.md).  The fixed-length list
        snapshots atomically under the GIL; see ``_versions`` for why
        the counters need no lock."""
        return tuple(self._versions)

    def touch_chain(self, request_keys: Sequence[int]) -> None:
        """Refresh recency for a previously-consumed chain (the score
        memo's hit path): keeps LRU eviction order identical to the
        walk the memo elides; missing keys are ignored."""
        self._touch_keys(request_keys)

    def _touch_keys(self, request_keys: Sequence[int]) -> None:
        """Batched recency refresh, grouped per shard."""
        if not self._mask:
            self._shards[0].touch_many(request_keys)
            return
        groups: Dict[int, List[int]] = {}
        mask = self._mask
        for key in request_keys:
            groups.setdefault(key & mask, []).append(key)
        for shard_index in sorted(groups):  # ascending shard order
            self._shards[shard_index].touch_many(groups[shard_index])

    # -- read path ------------------------------------------------------

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")

        pods_per_key: Dict[int, List[PodEntry]] = {}
        # Batched lock round-trips for the whole chain instead of one
        # per key (a long-prompt lookup walks hundreds): peek first,
        # then refresh recency ONLY for keys that yielded pods — never
        # the dead break key or the unreachable suffix, which would
        # push live entries out under LRU pressure.  Deferring the
        # touch does widen the window in which a concurrent add can
        # evict a key this lookup already read (the old per-key get
        # made each key MRU before examining the next); that race
        # existed between get and snapshot anyway, and the index is
        # advisory — continuously rebuilt from engine events — so a
        # transiently stale read is the accepted cost of the batching.
        caches = self._peek_ordered(request_keys)
        touched: List[int] = []
        for key, pod_cache in zip(request_keys, caches):
            if pod_cache is None:
                continue
            pods = pod_cache.snapshot()
            if not pods:
                # The prefix chain is broken here for every pod: stop.
                break
            touched.append(key)
            selected: List[PodEntry]
            if pod_identifier_set:
                # Filter only when something is actually filtered out
                # (the common case passes every pod the index knows
                # about — the old code built a filtered copy per key
                # regardless).
                covered = True
                for entry in pods:
                    if entry.pod_identifier not in pod_identifier_set:
                        covered = False
                        break
                if covered:
                    selected = list(pods)
                else:
                    selected = [
                        p
                        for p in pods
                        if p.pod_identifier in pod_identifier_set
                    ]
            else:
                selected = list(pods)
            if selected:
                pods_per_key[key] = selected
        if touched:
            self._touch_keys(touched)
        return pods_per_key

    def lookup_chain(
        self, request_keys: Sequence[int]
    ) -> List[Sequence[PodEntry]]:
        """Aligned, unfiltered per-key pod snapshots for the fast-lane
        scoring walk (see ``Index.lookup_chain``): stops at the first
        key with no resident pods, allocates no per-key dicts or
        filtered copies (the scorer filters inline), and refreshes
        recency only for the keys consumed.  The shard grouping built
        for the peek pass is reused for the recency pass when the whole
        chain was consumed (the steady-state warm case), so a request
        pays one grouping walk, not two."""
        out: List[Sequence[PodEntry]] = []
        if not self._mask:
            shard = self._shards[0]
            caches = shard.peek_many(request_keys)
            for pod_cache in caches:
                if pod_cache is None:
                    break
                pods = pod_cache.snapshot()
                if not pods:
                    break
                out.append(pods)
            if out:
                shard.touch_many(request_keys[: len(out)])
            return out

        n_keys = len(request_keys)
        groups = self._chain_groups(request_keys)
        flat = self._peek_ordered(request_keys, groups)
        for pod_cache in flat:
            if pod_cache is None:
                break
            pods = pod_cache.snapshot()
            if not pods:
                break
            out.append(pods)
        consumed = len(out)
        if consumed == n_keys:
            for shard_index in sorted(groups):  # ascending shard order
                self._shards[shard_index].touch_many(groups[shard_index][1])
        elif consumed:
            self._touch_keys(request_keys[:consumed])
        return out

    # -- write path -----------------------------------------------------

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for add")
        if len(engine_keys) != len(request_keys):
            raise ValueError(
                "engine keys and request keys length mismatch: "
                f"{len(engine_keys)} != {len(request_keys)}"
            )
        self.add_mappings(engine_keys, request_keys)
        self.add_entries_batch([(request_keys, entries)])

    def add_mappings(
        self, engine_keys: Sequence[int], request_keys: Sequence[int]
    ) -> None:
        """Publish engine->request key mappings (one lock round-trip).

        Split out of :meth:`add` so the batched kvevents apply path can
        publish mappings eagerly — later events in the same batch
        resolve their parents through ``get_request_key`` — while pod
        entries are still being grouped per shard.
        """
        self._engine_to_request.put_many(
            list(zip(engine_keys, request_keys))
        )

    def add_entries_batch(
        self,
        items: Sequence[Tuple[Sequence[int], Sequence[PodEntry]]],
    ) -> None:
        """Admit ``(request_keys, entries)`` groups, per-shard batched.

        All request keys across ``items`` are grouped by shard first, so
        each shard's lock is taken once per call instead of once per
        key (the kvevents batched apply path drains tens of messages
        per wake-up; see docs/performance.md).
        """
        mask = self._mask
        pod_cache_size = self.config.pod_cache_size
        # shard index -> ([request_key, ...], [entries_per_key, ...])
        groups: Dict[int, Tuple[List[int], List[Sequence[PodEntry]]]] = {}
        for request_keys, entries in items:
            for request_key in request_keys:
                group = groups.get(request_key & mask)
                if group is None:
                    group = groups[request_key & mask] = ([], [])
                group[0].append(request_key)
                group[1].append(entries)
        for shard_index in sorted(groups):  # ascending shard order
            keys, entry_lists = groups[shard_index]
            caches = self._shards[shard_index].get_or_create_many(
                keys, lambda: _PodCache(pod_cache_size)
            )
            for pod_cache, entries in zip(caches, entry_lists):
                pod_cache.add_all(entries)
        if groups:
            self._bump_shards(groups.keys())

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction")

        request_key = self._engine_to_request.get(engine_key)
        if request_key is None:
            return
        shard = self._shard(request_key)
        pod_cache = shard.get(request_key)
        if pod_cache is None:
            self._engine_to_request.remove(engine_key)
            return

        if pod_cache.remove_all(entries):
            # Re-check under the current resident cache to narrow the race
            # with a concurrent add; worst case an empty cache lingers until
            # LRU pressure clears it.
            current = shard.get(request_key)
            if current is not None and len(current) == 0:
                shard.remove(request_key)
                self._engine_to_request.remove(engine_key)
        self._bump_shards((request_key & self._mask,))

    def get_request_key(self, engine_key: int) -> int:
        request_key = self._engine_to_request.get(engine_key)
        if request_key is None:
            raise KeyError(f"engine key not found: {engine_key:#x}")
        return request_key

    # -- persistence / admin --------------------------------------------

    def dump_entries(
        self,
    ) -> Tuple[List[Tuple[int, List[PodEntry]]], List[Tuple[int, int]]]:
        # keys() snapshots LRU-first per shard; the dump concatenates
        # shard segments, so the global order is least-recently-used
        # within each shard (exact only for shards=1) — a
        # capacity-bounded restore into the same shard layout re-evicts
        # the same per-shard victims.  A concurrent eviction between
        # the key snapshot and the per-key peek just drops that key
        # from the dump — the journal replays whatever raced past it.
        block_entries: List[Tuple[int, List[PodEntry]]] = []
        for shard in self._shards:
            for request_key in shard.keys():
                pod_cache = shard.peek(request_key)
                if pod_cache is None:
                    continue
                pods = list(pod_cache.snapshot())
                if pods:
                    block_entries.append((request_key, pods))
        engine_map = [
            (engine_key, request_key)
            for engine_key, request_key in self._engine_to_request.items()
        ]
        return block_entries, engine_map

    def restore_entries(
        self,
        block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
        engine_map: Sequence[Tuple[int, int]],
    ) -> int:
        restored = 0
        touched_shards: Set[int] = set()
        for request_key, pods in block_entries:
            if not pods:
                continue
            shard = self._shard(request_key)
            pod_cache = shard.get(request_key)
            if pod_cache is None:
                pod_cache = shard.put_if_absent(
                    request_key, _PodCache(self.config.pod_cache_size)
                )
            pod_cache.add_all(list(pods))
            touched_shards.add(request_key & self._mask)
            restored += 1
        for engine_key, request_key in engine_map:
            self._engine_to_request.put(engine_key, request_key)
        if touched_shards:
            self._bump_shards(touched_shards)
        return restored

    def request_keys(self) -> List[int]:
        """Resident request keys, concatenated per shard — the
        keys-only walk (no pod-cache snapshots, no entry lists) backing
        slice-scoped scans like the replication follower's purge
        replay.  Point-in-time per shard, like :meth:`dump_entries`."""
        out: List[int] = []
        for shard in self._shards:
            out.extend(shard.keys())
        return out

    def purge_pod_keys(
        self, pod_identifier: str, request_keys: Sequence[int]
    ) -> int:
        """Purge one pod's entries restricted to ``request_keys``.

        The replication follower's slice-scoped purge
        (docs/replication.md): replaying a PEER's pod-wide purge record
        against the whole local index would wipe admissions this
        replica applied to its OWN slice after the purge — so the
        follower purges only the keys of the peer's slice.  Keys whose
        pod set empties are removed exactly like :meth:`purge_pod`'s.
        """
        removed = 0
        touched: Set[int] = set()
        for request_key in request_keys:
            shard = self._shard(request_key)
            pod_cache = shard.get(request_key)
            if pod_cache is None:
                continue
            victims, now_empty = pod_cache.purge(pod_identifier)
            removed += victims
            if victims:
                touched.add(request_key & self._mask)
            if now_empty:
                current = shard.get(request_key)
                if current is not None and len(current) == 0:
                    shard.remove(request_key)
        if touched:
            self._bump_shards(touched)
        return removed

    def purge_pod(self, pod_identifier: str) -> int:
        removed = 0
        for shard in self._shards:
            for request_key in shard.keys():
                pod_cache = shard.get(request_key)
                if pod_cache is None:  # raced with LRU eviction
                    continue
                victims, now_empty = pod_cache.purge(pod_identifier)
                removed += victims
                if now_empty:
                    # An empty pod set would read as a broken prefix
                    # chain for EVERY pod (lookup early-stop); drop the
                    # key.  Re-check under the resident cache first
                    # (same race narrowing as evict()): a concurrent
                    # add may have published a fresh claim since the
                    # purge released the pod-cache lock.
                    current = shard.get(request_key)
                    if current is not None and len(current) == 0:
                        shard.remove(request_key)
        # Bump every shard AFTER the sweep (administrative op; shards
        # untouched by the purge over-invalidate the score memo, which
        # only costs a re-walk) — bumping first would let a concurrent
        # walk memoize partially-purged state under the new vector.
        self._bump_shards(range(len(self._shards)))
        return removed
