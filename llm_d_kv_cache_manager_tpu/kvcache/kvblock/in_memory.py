"""Default in-memory index: two bounded LRU maps.

``request_key -> PodCache`` (an LRU of PodEntry) plus
``engine_key -> request_key`` for evictions, mirroring the reference's
two-level design (pkg/kvcache/kvblock/in_memory.go:105-270) with a single
lock per pod-cache and atomic put-if-absent instead of Go's double-checked
insert.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    Index,
    InMemoryIndexConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache


class _PodCache:
    """Bounded recency set of PodEntry for one block key."""

    __slots__ = ("entries", "lock")

    def __init__(self, capacity: int) -> None:
        self.entries: LRUCache[PodEntry, None] = LRUCache(capacity)
        self.lock = threading.Lock()

    def add_all(self, entries: Sequence[PodEntry]) -> None:
        with self.lock:
            for entry in entries:
                self.entries.put(entry, None)

    def remove_all(self, entries: Sequence[PodEntry]) -> bool:
        """Remove entries; return True if the cache is now empty."""
        with self.lock:
            for entry in entries:
                self.entries.remove(entry)
            return len(self.entries) == 0

    def snapshot(self) -> List[PodEntry]:
        return self.entries.keys()

    def __len__(self) -> int:
        return len(self.entries)


class InMemoryIndex(Index):
    def __init__(self, config: Optional[InMemoryIndexConfig] = None) -> None:
        self.config = config or InMemoryIndexConfig()
        self._data: LRUCache[int, _PodCache] = LRUCache(self.config.size)
        self._engine_to_request: LRUCache[int, int] = LRUCache(self.config.size)

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")

        pods_per_key: Dict[int, List[PodEntry]] = {}
        # Two batched lock round-trips for the whole chain instead of
        # one per key (a long-prompt lookup walks hundreds): peek
        # first, then refresh recency ONLY for keys that yielded pods
        # — never the dead break key or the unreachable suffix, which
        # would push live entries out under LRU pressure.  Deferring
        # the touch does widen the window in which a concurrent add
        # can evict a key this lookup already read (the old per-key
        # get made each key MRU before examining the next); that race
        # existed between get and snapshot anyway, and the index is
        # advisory — continuously rebuilt from engine events — so a
        # transiently stale read is the accepted cost of the batching.
        caches = self._data.peek_many(request_keys)
        touched: List[int] = []
        for key, pod_cache in zip(request_keys, caches):
            if pod_cache is None:
                continue
            pods = pod_cache.snapshot()
            if not pods:
                # The prefix chain is broken here for every pod: stop.
                break
            touched.append(key)
            if pod_identifier_set:
                pods = [
                    p for p in pods if p.pod_identifier in pod_identifier_set
                ]
            if pods:
                pods_per_key[key] = pods
        if touched:
            self._data.touch_many(touched)
        return pods_per_key

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for add")
        if len(engine_keys) != len(request_keys):
            raise ValueError(
                "engine keys and request keys length mismatch: "
                f"{len(engine_keys)} != {len(request_keys)}"
            )

        for engine_key, request_key in zip(engine_keys, request_keys):
            self._engine_to_request.put(engine_key, request_key)
            pod_cache = self._data.get(request_key)
            if pod_cache is None:
                pod_cache = self._data.put_if_absent(
                    request_key, _PodCache(self.config.pod_cache_size)
                )
            pod_cache.add_all(entries)

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction")

        request_key = self._engine_to_request.get(engine_key)
        if request_key is None:
            return
        pod_cache = self._data.get(request_key)
        if pod_cache is None:
            self._engine_to_request.remove(engine_key)
            return

        if pod_cache.remove_all(entries):
            # Re-check under the current resident cache to narrow the race
            # with a concurrent add; worst case an empty cache lingers until
            # LRU pressure clears it.
            current = self._data.get(request_key)
            if current is not None and len(current) == 0:
                self._data.remove(request_key)
                self._engine_to_request.remove(engine_key)

    def get_request_key(self, engine_key: int) -> int:
        request_key = self._engine_to_request.get(engine_key)
        if request_key is None:
            raise KeyError(f"engine key not found: {engine_key:#x}")
        return request_key

    def dump_entries(
        self,
    ) -> Tuple[List[Tuple[int, List[PodEntry]]], List[Tuple[int, int]]]:
        # keys() snapshots LRU-first; a concurrent eviction between the
        # key snapshot and the per-key peek just drops that key from
        # the dump — the journal replays whatever raced past the dump.
        block_entries: List[Tuple[int, List[PodEntry]]] = []
        for request_key in self._data.keys():
            pod_cache = self._data.peek(request_key)
            if pod_cache is None:
                continue
            pods = pod_cache.snapshot()
            if pods:
                block_entries.append((request_key, pods))
        engine_map = [
            (engine_key, request_key)
            for engine_key, request_key in self._engine_to_request.items()
        ]
        return block_entries, engine_map

    def restore_entries(
        self,
        block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
        engine_map: Sequence[Tuple[int, int]],
    ) -> int:
        restored = 0
        for request_key, pods in block_entries:
            if not pods:
                continue
            pod_cache = self._data.get(request_key)
            if pod_cache is None:
                pod_cache = self._data.put_if_absent(
                    request_key, _PodCache(self.config.pod_cache_size)
                )
            pod_cache.add_all(list(pods))
            restored += 1
        for engine_key, request_key in engine_map:
            self._engine_to_request.put(engine_key, request_key)
        return restored

    def purge_pod(self, pod_identifier: str) -> int:
        removed = 0
        for request_key in self._data.keys():
            pod_cache = self._data.get(request_key)
            if pod_cache is None:  # raced with LRU eviction
                continue
            with pod_cache.lock:
                victims = [
                    entry
                    for entry in pod_cache.entries.keys()
                    if entry.pod_identifier == pod_identifier
                ]
                for entry in victims:
                    pod_cache.entries.remove(entry)
                removed += len(victims)
                now_empty = len(pod_cache.entries) == 0
            if now_empty:
                # An empty pod set would read as a broken prefix chain
                # for EVERY pod (lookup early-stop); drop the key.
                # Re-check under the resident cache first (same race
                # narrowing as evict()): a concurrent add may have
                # published a fresh claim since the lock was released.
                current = self._data.get(request_key)
                if current is not None and len(current) == 0:
                    self._data.remove(request_key)
        return removed
