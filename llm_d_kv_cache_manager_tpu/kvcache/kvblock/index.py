"""KV-block index contract and backend factory.

The index answers one question fast: *which pods hold which KV blocks, on
which memory tier?*  It is written by the KVEvents ingestion pool and read
by the scoring path.

Dual-key design (the subtle core, reference pkg/kvcache/kvblock/index.go and
pool.go:272-292): *engine keys* are whatever hashes an engine pod reports —
possibly seeded differently or sha256-truncated — while *request keys* are
recomputed locally from the event's token IDs with the indexer's own hash
chain.  Lookups from prompts produce request keys, so routing works
regardless of per-engine hash configuration; the engine->request mapping
exists so evictions (which carry engine keys) can find the entry.

TPU tier vocabulary: events from TPU pods carry ``Medium`` in
{"hbm", "host", "shared_storage"}; GPU-era names ("gpu", "cpu") are accepted
for wire compatibility and mapped by the scorer's weight table.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
)

__all__ = [
    "EMPTY_BLOCK_HASH",
    "PodEntry",
    "Index",
    "IndexConfig",
    "InMemoryIndexConfig",
    "CostAwareIndexConfig",
    "RedisIndexConfig",
    "new_index",
]


@dataclass(frozen=True)
class PodEntry:
    """A (pod, device-tier) pair holding some KV block."""

    pod_identifier: str
    device_tier: str

    def __str__(self) -> str:
        return f"{self.pod_identifier}@{self.device_tier}"


class Index(ABC):
    """Pluggable KV-block index backend."""

    @abstractmethod
    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        """Return pods per key, filtered to ``pod_identifier_set`` if given.

        Keys absent from the index are simply missing from the result; a key
        present with an empty pod set terminates the scan early (the prefix
        chain is broken there for every pod).
        """

    def lookup_chain(
        self, request_keys: Sequence[int]
    ) -> List[Sequence[PodEntry]]:
        """Aligned per-key pod entries for a consecutive prefix chain.

        The read-path fast lane's lookup shape: ``result[i]`` holds the
        (unfiltered) pods for ``request_keys[i]``; the walk stops at
        the first key with no resident pods, so the result may be
        shorter than the input — a truncated result means the prefix
        chain is dead there for every pod.  Pod filtering happens in
        the scorer (``LongestPrefixScorer.advance``), which never
        changes scores relative to ``lookup`` + ``score`` (pinned by
        the fast-lane parity tests).  Backends may override with a
        dict-free implementation; this default adapts :meth:`lookup`.
        """
        found = self.lookup(request_keys, None)
        out: List[Sequence[PodEntry]] = []
        for key in request_keys:
            pods = found.get(key)
            if not pods:
                break
            out.append(pods)
        return out

    @abstractmethod
    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        """Record that ``entries`` hold the blocks named by the key pairs."""

    @abstractmethod
    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        """Remove ``entries`` from the block named by ``engine_key``."""

    @abstractmethod
    def get_request_key(self, engine_key: int) -> int:
        """Map an engine key to its request key.

        Raises ``KeyError`` if the mapping is missing (e.g. already
        evicted).
        """

    @abstractmethod
    def dump_entries(
        self,
    ) -> Tuple[List[Tuple[int, List[PodEntry]]], List[Tuple[int, int]]]:
        """Serialize the index for a persistence snapshot.

        Returns ``(block_entries, engine_map)``: ``block_entries`` is
        ``[(request_key, [PodEntry, ...]), ...]`` and ``engine_map`` is
        ``[(engine_key, request_key), ...]``.  Both are ordered
        least-recently-used first so a capacity-bounded
        :meth:`restore_entries` re-evicts the same victims the live
        index would have.  The dump is a point-in-time snapshot taken
        under the backend's own locking discipline; concurrent writers
        may land either side of it (the persistence journal covers the
        gap — see ``persistence/``).

        Durable backends (Redis/Valkey) answer too — a SCAN-walked
        dump in server iteration order (no recency available) — so
        replica-duty surfaces (cluster parity, follower bootstrap, the
        index auditor) see one contract; snapshotting a durable server
        through the file layer is still usually redundant
        (docs/persistence.md).
        """

    @abstractmethod
    def restore_entries(
        self,
        block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
        engine_map: Sequence[Tuple[int, int]],
    ) -> int:
        """Load a :meth:`dump_entries` dump; returns block keys restored.

        Applies the dump through the backend's normal admission path, so
        capacity/budget bounds hold (an oversized dump is truncated by
        the same LRU policy as live traffic; Redis defers to the
        server's own maxmemory policy).  Safe on a non-empty index:
        restoring an entry that already exists is idempotent.
        """

    @abstractmethod
    def purge_pod(self, pod_identifier: str) -> int:
        """Drop every entry for one pod; returns entries removed.

        Administrative recovery operation (O(index size); not a hot
        path): when a pod dies or its event stream gaps badly, its
        stale entries keep attracting traffic until LRU churn clears
        them — the reference simply lets them linger.  Keys whose pod
        set empties are removed entirely so they cannot break other
        pods' prefix chains at lookup.  Engine-key mappings may
        linger, exactly as after an LRU eviction.
        """


@dataclass
class InMemoryIndexConfig:
    # Maximum number of block keys resident; TODO memory-based sizing.
    size: int = 100_000_000
    # Maximum pod entries tracked per key.
    pod_cache_size: int = 10
    # Lock stripes for the request-key map (rounded up to a power of
    # two).  Concurrent scoring reads and kvevents applies touching
    # different shards never share a lock; capacity is budgeted per
    # shard, so the global ``size`` bound is approximate unless
    # ``shards=1`` (exact single-LRU semantics).  See
    # docs/performance.md.
    shards: int = 8


@dataclass
class CostAwareIndexConfig:
    # Approximate memory budget for the index, in bytes (default 2 GiB).
    max_cost_bytes: int = 2 * 1024 * 1024 * 1024
    pod_cache_size: int = 10
    # Predictive eviction ranking (tiering/eviction.py): an object with
    # ``select_victim(candidates, now) -> index`` and a ``sample`` size,
    # called under the index lock with an LRU-ordered (key, byte-cost)
    # sample — it must take no locks of its own (it ranks against an
    # immutable policy snapshot).  None keeps the pristine
    # pop-LRU-first path, bit-identical to pre-tiering behavior (the
    # parity oracle; docs/tiering.md).
    eviction_policy: Optional[object] = None


@dataclass
class RedisIndexConfig:
    # Accepts bare host:port or redis:// | rediss:// | valkey:// |
    # valkeys:// | unix:// URLs, with optional user:pass@ credentials and
    # /db suffix (reference: redis.go:61-119 via go-redis ParseURL).
    address: str = "127.0.0.1:6379"
    # "redis" or "valkey"; valkey:// URLs are rewritten to redis:// with the
    # same host/port (valkeys:// to rediss://).
    flavor: str = "redis"
    # TLS options for rediss:// endpoints.
    tls_ca_file: Optional[str] = None
    tls_insecure_skip_verify: bool = False


@dataclass
class IndexConfig:
    """Backend selection; priority cost-aware > redis > in-memory
    (reference: kvblock/index.go:59-105)."""

    in_memory_config: Optional[InMemoryIndexConfig] = field(
        default_factory=InMemoryIndexConfig
    )
    cost_aware_config: Optional[CostAwareIndexConfig] = None
    redis_config: Optional[RedisIndexConfig] = None
    enable_metrics: bool = False


def new_index(config: Optional[IndexConfig] = None) -> Index:
    """Build the configured index backend, optionally metrics-wrapped."""
    if config is None:
        config = IndexConfig()

    index: Index
    if config.cost_aware_config is not None:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
            CostAwareMemoryIndex,
        )

        index = CostAwareMemoryIndex(config.cost_aware_config)
    elif config.redis_config is not None:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
            RedisIndex,
        )

        index = RedisIndex(config.redis_config)
    else:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )

        index = InMemoryIndex(config.in_memory_config)

    if config.enable_metrics:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import (
            InstrumentedIndex,
        )

        index = InstrumentedIndex(index)
    return index
