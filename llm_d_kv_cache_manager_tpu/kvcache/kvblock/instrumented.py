"""Metrics decorator for any Index backend.

Decorator-pattern instrumentation emitting admissions / evictions / lookup
counters and a lookup-latency histogram, plus the per-lookup max-hits-per-pod
gauge the scorer's telemetry relies on (capability parity:
pkg/kvcache/kvblock/instrumented_index.go).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Set

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, PodEntry
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS


class InstrumentedIndex(Index):
    def __init__(self, inner: Index) -> None:
        self._inner = inner

    @property
    def inner(self) -> Index:
        return self._inner

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        METRICS.index_lookup_requests.inc()
        start = time.perf_counter()
        try:
            result = self._inner.lookup(request_keys, pod_identifier_set)
        finally:
            METRICS.index_lookup_latency.observe(time.perf_counter() - start)
        if result:
            METRICS.index_lookup_hits.inc()
            hits_per_pod: Dict[str, int] = {}
            for pods in result.values():
                for pod in pods:
                    hits_per_pod[pod.pod_identifier] = (
                        hits_per_pod.get(pod.pod_identifier, 0) + 1
                    )
            if hits_per_pod:
                METRICS.index_max_pod_hits.inc(max(hits_per_pod.values()))
        return result

    def lookup_chain(
        self, request_keys: Sequence[int]
    ) -> List[Sequence[PodEntry]]:
        # Deliberately un-instrumented: the fast lane calls this once
        # per CHUNK, and counting per call would silently inflate the
        # lookup counters relative to the straight path (one logical
        # lookup per scoring request).  The Indexer records one
        # request-granularity observation per chunked drive instead
        # (record_chain_lookup below).
        return self._inner.lookup_chain(request_keys)

    @staticmethod
    def record_chain_lookup(
        latency_s: float, max_pod_hits: int
    ) -> None:
        """One scoring request's chunked lookup, request-granular —
        the same meaning lookup() records per call: requests +1, hits
        +1 when any pod matched, total lookup latency, and the max
        per-pod hit count across the whole chain."""
        METRICS.index_lookup_requests.inc()
        METRICS.index_lookup_latency.observe(latency_s)
        if max_pod_hits:
            METRICS.index_lookup_hits.inc()
            METRICS.index_max_pod_hits.inc(max_pod_hits)

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        self._inner.add(engine_keys, request_keys, entries)
        METRICS.index_admissions.inc(len(request_keys))

    # Batched-apply capability passthrough: kvevents/pool.py probes for
    # add_mappings/add_entries_batch with getattr, so the wrapper must
    # neither mask a backend that has them nor fake them on a backend
    # that does not — hence __getattr__ (which only fires for names NOT
    # defined on this class) instead of plain methods.

    def __getattr__(self, name: str):
        if name in (
            "add_mappings",
            "version_vector",
            "touch_chain",
            "lookup_chain_async",
            "record_speculation",
        ):
            # version_vector/touch_chain: the indexer's score memo
            # probes for the optimistic-validation surface the same
            # way (getattr), and neither needs metrics of its own.
            # lookup_chain_async/record_speculation: the pipelined
            # chunk drive probes for the async surface the same way;
            # like lookup_chain, the async variant is deliberately
            # un-instrumented — the fast lane records one
            # request-granular observation itself
            # (record_chain_lookup).
            return getattr(self._inner, name)
        if name == "add_entries_batch":
            inner_batch = getattr(self._inner, name)

            def add_entries_batch(items) -> None:
                inner_batch(items)
                METRICS.index_admissions.inc(
                    sum(len(request_keys) for request_keys, _ in items)
                )

            return add_entries_batch
        raise AttributeError(name)

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        self._inner.evict(engine_key, entries)
        METRICS.index_evictions.inc()

    def get_request_key(self, engine_key: int) -> int:
        return self._inner.get_request_key(engine_key)

    def dump_entries(self):
        return self._inner.dump_entries()

    def restore_entries(self, block_entries, engine_map) -> int:
        restored = self._inner.restore_entries(block_entries, engine_map)
        if restored:
            METRICS.index_admissions.inc(restored)
        return restored

    def purge_pod(self, pod_identifier: str) -> int:
        removed = self._inner.purge_pod(pod_identifier)
        if removed:
            METRICS.index_evictions.inc(removed)
        return removed
