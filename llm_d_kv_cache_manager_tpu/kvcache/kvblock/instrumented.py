"""Metrics decorator for any Index backend.

Decorator-pattern instrumentation emitting admissions / evictions / lookup
counters and a lookup-latency histogram, plus the per-lookup max-hits-per-pod
gauge the scorer's telemetry relies on (capability parity:
pkg/kvcache/kvblock/instrumented_index.go).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Set

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, PodEntry
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS


class InstrumentedIndex(Index):
    def __init__(self, inner: Index) -> None:
        self._inner = inner

    @property
    def inner(self) -> Index:
        return self._inner

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        METRICS.index_lookup_requests.inc()
        start = time.perf_counter()
        try:
            result = self._inner.lookup(request_keys, pod_identifier_set)
        finally:
            METRICS.index_lookup_latency.observe(time.perf_counter() - start)
        if result:
            METRICS.index_lookup_hits.inc()
            hits_per_pod: Dict[str, int] = {}
            for pods in result.values():
                for pod in pods:
                    hits_per_pod[pod.pod_identifier] = (
                        hits_per_pod.get(pod.pod_identifier, 0) + 1
                    )
            if hits_per_pod:
                METRICS.index_max_pod_hits.inc(max(hits_per_pod.values()))
        return result

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        self._inner.add(engine_keys, request_keys, entries)
        METRICS.index_admissions.inc(len(request_keys))

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        self._inner.evict(engine_key, entries)
        METRICS.index_evictions.inc()

    def get_request_key(self, engine_key: int) -> int:
        return self._inner.get_request_key(engine_key)

    def dump_entries(self):
        return self._inner.dump_entries()

    def restore_entries(self, block_entries, engine_map) -> int:
        restored = self._inner.restore_entries(block_entries, engine_map)
        if restored:
            METRICS.index_admissions.inc(restored)
        return restored

    def purge_pod(self, pod_identifier: str) -> int:
        removed = self._inner.purge_pod(pod_identifier)
        if removed:
            METRICS.index_evictions.inc(removed)
        return removed
