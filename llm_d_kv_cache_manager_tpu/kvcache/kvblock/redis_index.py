"""Distributed index backed by Redis / Valkey.

Capability parity with the reference's Redis backend
(pkg/kvcache/kvblock/redis.go): the shared schema is

* ``<request_key>``          -> Redis hash; fields are ``"pod@tier"``
* ``engine:<engine_key>``    -> string holding the request key

Lookups pipeline one ``HKEYS`` per block key in a single round trip; adds
pipeline ``HSET`` + ``SET``; evictions remove fields and prune empty hashes.
Valkey endpoints (``valkey://``) speak the same protocol and are accepted.

The image ships no redis-py, so this module carries a deliberately small
RESP2 client (sockets + pipelining) — the indexer only needs six commands.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    Index,
    PodEntry,
    RedisIndexConfig,
)


class RespError(RuntimeError):
    """A server-side error reply (``-ERR ...``)."""


class RespClient:
    """Minimal RESP2 client with pipelining and transparent reconnect."""

    def __init__(self, host: str, port: int, timeout: float = 5.0) -> None:
        self._host = host
        self._port = port
        self._timeout = timeout
        self._sock = None
        self._reader = None
        self._lock = threading.Lock()
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        # Small request/reply packets: Nagle + delayed ACK otherwise adds
        # ~40ms stalls per pipelined round trip.
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = self._sock.makefile("rb")

    def close(self) -> None:
        if self._reader is not None:
            try:
                self._reader.close()
            except OSError:
                pass
            self._reader = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    @staticmethod
    def _encode(command: Sequence) -> bytes:
        parts = [b"*%d\r\n" % len(command)]
        for arg in command:
            if isinstance(arg, str):
                arg = arg.encode()
            elif isinstance(arg, int):
                arg = str(arg).encode()
            parts.append(b"$%d\r\n%s\r\n" % (len(arg), arg))
        return b"".join(parts)

    def _read_reply(self):
        """Read one reply; server error replies are *returned* as RespError
        instances (not raised) so a pipeline never desyncs the stream."""
        line = self._reader.readline()
        if not line:
            raise ConnectionError("connection closed by server")
        kind, payload = line[:1], line[1:-2]
        if kind == b"+":
            return payload.decode()
        if kind == b"-":
            return RespError(payload.decode())
        if kind == b":":
            return int(payload)
        if kind == b"$":
            length = int(payload)
            if length == -1:
                return None
            data = self._reader.read(length + 2)
            if len(data) != length + 2:
                raise ConnectionError("short read from server")
            return data[:-2]
        if kind == b"*":
            count = int(payload)
            if count == -1:
                return None
            return [self._read_reply() for _ in range(count)]
        raise ConnectionError(f"unknown RESP type: {kind!r}")

    def execute(self, *command):
        return self.pipeline([command])[0]

    def pipeline(self, commands: Iterable[Sequence]) -> List:
        """Send all commands, read all replies; raise the first server error
        only after the stream is fully drained.  On transport errors the
        connection is torn down and retried once on a fresh socket."""
        commands = list(commands)
        if not commands:
            return []
        payload = b"".join(self._encode(c) for c in commands)
        with self._lock:
            replies = self._round_trip_locked(payload, len(commands))
        for reply in replies:
            if isinstance(reply, RespError):
                raise reply
        return replies

    def _round_trip_locked(self, payload: bytes, count: int) -> List:
        for attempt in (0, 1):
            try:
                if self._sock is None:
                    self._connect()
                self._sock.sendall(payload)
                return [self._read_reply() for _ in range(count)]
            except (OSError, ConnectionError):
                self.close()
                if attempt == 1:
                    raise
        raise AssertionError("unreachable")


def _parse_address(address: str) -> Tuple[str, int]:
    address = address.strip()
    if address.startswith("rediss://"):
        raise ValueError(
            "rediss:// (TLS) endpoints are not supported by the built-in "
            "RESP client; terminate TLS in front of the indexer instead"
        )
    for scheme in ("redis://", "valkey://"):
        if address.startswith(scheme):
            address = address[len(scheme):]
            break
    address = address.split("/", 1)[0]
    if "@" in address:
        raise ValueError(
            "credentials in the redis address are not supported (AUTH is "
            "not implemented); use an unauthenticated endpoint"
        )
    host, _, port = address.partition(":")
    return host or "127.0.0.1", int(port or 6379)


_ENGINE_PREFIX = "engine:"


class RedisIndex(Index):
    def __init__(
        self,
        config: Optional[RedisIndexConfig] = None,
        client: Optional[RespClient] = None,
    ) -> None:
        self.config = config or RedisIndexConfig()
        if client is None:
            host, port = _parse_address(self.config.address)
            client = RespClient(host, port)
        self._client = client

    @staticmethod
    def _field(entry: PodEntry) -> str:
        return f"{entry.pod_identifier}@{entry.device_tier}"

    @staticmethod
    def _parse_field(field: bytes) -> Optional[PodEntry]:
        text = field.decode()
        pod, sep, tier = text.rpartition("@")
        if not sep:
            return None
        return PodEntry(pod_identifier=pod, device_tier=tier)

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")
        replies = self._client.pipeline(
            [("HKEYS", str(key)) for key in request_keys]
        )
        result: Dict[int, List[PodEntry]] = {}
        for key, fields in zip(request_keys, replies):
            if not fields:
                continue
            pods = []
            for field in fields:
                entry = self._parse_field(field)
                if entry is None:
                    continue
                if (
                    pod_identifier_set
                    and entry.pod_identifier not in pod_identifier_set
                ):
                    continue
                pods.append(entry)
            if pods:
                result[key] = pods
        return result

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for add")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")
        commands: List[Sequence] = []
        for engine_key, request_key in zip(engine_keys, request_keys):
            hset: List = ["HSET", str(request_key)]
            for entry in entries:
                hset += [self._field(entry), "1"]
            commands.append(hset)
            commands.append(
                ("SET", f"{_ENGINE_PREFIX}{engine_key}", str(request_key))
            )
        self._client.pipeline(commands)

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction")
        request_key_raw = self._client.execute(
            "GET", f"{_ENGINE_PREFIX}{engine_key}"
        )
        if request_key_raw is None:
            return
        request_key = request_key_raw.decode()
        hdel: List = ["HDEL", request_key]
        hdel += [self._field(entry) for entry in entries]
        _, remaining = self._client.pipeline(
            [hdel, ("HLEN", request_key)]
        )
        if remaining == 0:
            # Benign race window with a concurrent add, as in the reference's
            # Lua prune; an empty hash left behind is harmless.
            self._client.pipeline(
                [
                    ("DEL", request_key),
                    ("DEL", f"{_ENGINE_PREFIX}{engine_key}"),
                ]
            )

    def get_request_key(self, engine_key: int) -> int:
        raw = self._client.execute("GET", f"{_ENGINE_PREFIX}{engine_key}")
        if raw is None:
            raise KeyError(f"engine key not found: {engine_key:#x}")
        return int(raw.decode())
