"""Distributed index backed by Redis / Valkey.

Capability parity with the reference's Redis backend
(pkg/kvcache/kvblock/redis.go): the shared schema is

* ``<request_key>``          -> Redis hash; fields are ``"pod@tier"``
* ``engine:<engine_key>``    -> string holding the request key

Lookups pipeline one ``HKEYS`` per block key in a single round trip; adds
pipeline ``HSET`` + ``SET``; evictions remove fields and atomically prune
the engine mapping with a server-side Lua script (reference:
redis.go:147-154).  Valkey endpoints (``valkey://``/``valkeys://``) speak
the same protocol and are accepted; URLs may carry credentials (AUTH on
connect), a ``/db`` index (SELECT), TLS (``rediss://``), or a ``unix://``
socket path.

The image ships no redis-py, so this module carries a deliberately small
RESP2 client (sockets + pipelining) — the indexer needs only a handful of
commands (HSET/HKEYS/HDEL/SET/GET/DEL plus EVAL, AUTH, SELECT).
"""

from __future__ import annotations

import socket
import ssl
import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple
from urllib.parse import unquote, urlparse

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    Index,
    PodEntry,
    RedisIndexConfig,
)


class RespError(RuntimeError):
    """A server-side error reply (``-ERR ...``)."""


@dataclass
class RedisEndpoint:
    """A parsed redis/valkey URL (scheme-normalized, credential-aware)."""

    host: str = "127.0.0.1"
    port: int = 6379
    unix_path: Optional[str] = None
    username: Optional[str] = None
    password: Optional[str] = None
    db: int = 0
    tls: bool = False


class RespClient:
    """Minimal RESP2 client with pipelining and transparent reconnect.

    The connection handshake (TLS, AUTH, SELECT) lives in ``_connect`` so
    it is replayed automatically when the transport reconnects.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        timeout: float = 5.0,
        endpoint: Optional[RedisEndpoint] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
    ) -> None:
        self._endpoint = endpoint or RedisEndpoint(host=host, port=port)
        self._timeout = timeout
        self._ssl_context = ssl_context
        if self._endpoint.tls and ssl_context is None:
            self._ssl_context = ssl.create_default_context()
        self._sock = None
        self._reader = None
        self._lock = threading.Lock()
        self._connect()

    def _connect(self) -> None:
        ep = self._endpoint
        if ep.unix_path is not None:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self._timeout)
            sock.connect(ep.unix_path)
        else:
            sock = socket.create_connection(
                (ep.host, ep.port), timeout=self._timeout
            )
            # Small request/reply packets: Nagle + delayed ACK otherwise
            # adds ~40ms stalls per pipelined round trip.
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._ssl_context is not None:
                sock = self._ssl_context.wrap_socket(
                    sock, server_hostname=ep.host
                )
        # gil-atomic: connect/close are single-owner (caller-serialized)
        self._sock = sock
        # gil-atomic: connect/close are single-owner (caller-serialized)
        self._reader = sock.makefile("rb")
        self._handshake()

    def _handshake(self) -> None:
        """AUTH + SELECT on the fresh connection (reference accepts
        credentialed URLs via go-redis ParseURL, redis.go:61-119)."""
        ep = self._endpoint
        commands: List[Sequence] = []
        # Empty password means "no AUTH" (go-redis ParseURL parity).
        if ep.password:
            if ep.username:
                commands.append(("AUTH", ep.username, ep.password))
            else:
                commands.append(("AUTH", ep.password))
        if ep.db:
            commands.append(("SELECT", str(ep.db)))
        if not commands:
            return
        payload = b"".join(self._encode(c) for c in commands)
        self._sock.sendall(payload)
        for _ in commands:
            reply = self._read_reply()
            if isinstance(reply, RespError):
                self.close()
                raise reply

    def close(self) -> None:
        if self._reader is not None:
            try:
                self._reader.close()
            except OSError:
                pass
            # gil-atomic: connect/close are single-owner (caller-serialized)
            self._reader = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            # gil-atomic: connect/close are single-owner (caller-serialized)
            self._sock = None

    @staticmethod
    def _encode(command: Sequence) -> bytes:
        parts = [b"*%d\r\n" % len(command)]
        for arg in command:
            if isinstance(arg, str):
                arg = arg.encode()
            elif isinstance(arg, int):
                arg = str(arg).encode()
            parts.append(b"$%d\r\n%s\r\n" % (len(arg), arg))
        return b"".join(parts)

    # Caps on attacker/misconfiguration-controlled sizes (e.g. the URL
    # points at an HTTP port, or a proxy garbles the stream): redis's own
    # proto-max-bulk-len default, and an array bound far above any reply
    # the index issues.
    _MAX_BULK = 512 * 1024 * 1024
    _MAX_ARRAY = 1 << 22
    _MAX_DEPTH = 32
    # Type-line bound: real RESP lines are tiny (a type byte + an
    # integer or a short status).  Without a limit, readline() on a
    # newline-free hostile stream buffers it whole before any other cap
    # is consulted.
    _MAX_LINE = 64 * 1024

    def _read_reply(self, depth: int = 0):
        """Read one reply; server error replies are *returned* as RespError
        instances (not raised) so a pipeline never desyncs the stream.

        Any malformed frame raises ConnectionError — not ValueError /
        UnicodeDecodeError / RecursionError — because a garbled stream
        means the connection is unusable: _round_trip_locked must tear
        it down and reconnect rather than keep pipelining on a desynced
        socket."""
        if depth > self._MAX_DEPTH:
            raise ConnectionError("RESP reply nested too deeply")
        line = self._reader.readline(self._MAX_LINE)
        if not line:
            raise ConnectionError("connection closed by server")
        if not line.endswith(b"\r\n"):
            # Truncated stream, or a line at the limit with no newline.
            raise ConnectionError(f"malformed RESP line: {line[:64]!r}")
        kind, payload = line[:1], line[1:-2]
        if kind == b"+":
            return payload.decode("utf-8", "replace")
        if kind == b"-":
            return RespError(payload.decode("utf-8", "replace"))
        if kind == b":":
            return self._parse_int(payload)
        if kind == b"$":
            length = self._parse_int(payload)
            if length == -1:
                return None
            if not 0 <= length <= self._MAX_BULK:
                raise ConnectionError(f"bad RESP bulk length {length}")
            data = self._reader.read(length + 2)
            if len(data) != length + 2:
                raise ConnectionError("short read from server")
            if data[-2:] != b"\r\n":
                # Wrong-length garbled frame: without this check the
                # stripped payload would be returned as a *successful*
                # reply and the stream left desynced.
                raise ConnectionError("bulk reply missing terminator")
            return data[:-2]
        if kind == b"*":
            count = self._parse_int(payload)
            if count == -1:
                return None
            if not 0 <= count <= self._MAX_ARRAY:
                raise ConnectionError(f"bad RESP array length {count}")
            return [self._read_reply(depth + 1) for _ in range(count)]
        raise ConnectionError(f"unknown RESP type: {kind!r}")

    @staticmethod
    def _parse_int(payload: bytes) -> int:
        # RESP grammar, not Python's int() (which accepts underscores,
        # whitespace, and '+': a corrupted b"1_0" must not parse as 10).
        digits = payload[1:] if payload[:1] == b"-" else payload
        if not digits or not digits.isdigit():
            raise ConnectionError(
                f"malformed RESP integer: {payload[:64]!r}"
            )
        return int(payload)

    def execute(self, *command):
        return self.pipeline([command])[0]

    def pipeline(
        self, commands: Iterable[Sequence], raise_on_error: bool = True
    ) -> List:
        """Send all commands, read all replies; raise the first server error
        only after the stream is fully drained.  On transport errors the
        connection is torn down and retried once on a fresh socket.
        ``raise_on_error=False`` returns ``RespError`` instances in place
        so callers can tolerate per-key failures (e.g. WRONGTYPE from
        foreign keys in a shared database)."""
        commands = list(commands)
        if not commands:
            return []
        payload = b"".join(self._encode(c) for c in commands)
        with self._lock:
            replies = self._round_trip_locked(payload, len(commands))
        if raise_on_error:
            for reply in replies:
                if isinstance(reply, RespError):
                    raise reply
        return replies

    def _round_trip_locked(self, payload: bytes, count: int) -> List:
        for attempt in (0, 1):
            try:
                if self._sock is None:
                    self._connect()
                self._sock.sendall(payload)
                return [self._read_reply() for _ in range(count)]
            except (OSError, ConnectionError):
                self.close()
                if attempt == 1:
                    raise
        raise AssertionError("unreachable")


def parse_redis_url(address: str) -> RedisEndpoint:
    """Parse a redis/valkey URL into a :class:`RedisEndpoint`.

    Mirrors the reference's normalization (redis.go:72-90): bare
    ``host:port`` defaults to ``redis://``; ``valkey://`` is rewritten to
    ``redis://`` and ``valkeys://`` to ``rediss://`` (TLS); ``unix://``
    selects a Unix-domain socket.  Credentials (``user:pass@``) and a
    trailing ``/db`` index are honored like go-redis ``ParseURL``.
    """
    address = address.strip()
    if "://" not in address:
        address = "redis://" + address
    if address.startswith("valkey://"):
        address = "redis://" + address[len("valkey://"):]
    elif address.startswith("valkeys://"):
        address = "rediss://" + address[len("valkeys://"):]

    parsed = urlparse(address)
    if parsed.scheme not in ("redis", "rediss", "unix"):
        raise ValueError(f"unsupported redis URL scheme: {parsed.scheme!r}")

    endpoint = RedisEndpoint(tls=parsed.scheme == "rediss")
    if parsed.username:
        endpoint.username = unquote(parsed.username)
    if parsed.password is not None:
        endpoint.password = unquote(parsed.password)

    # go-redis parity: ?db=N (the only way to select a db on a unix
    # socket); any other query key is rejected loudly rather than
    # silently ignored.
    if parsed.query:
        for pair in parsed.query.split("&"):
            key, _, raw = pair.partition("=")
            if key == "db":
                try:
                    endpoint.db = int(raw)
                except ValueError as e:
                    raise ValueError(
                        f"invalid db index in redis URL query: {raw!r}"
                    ) from e
            else:
                raise ValueError(
                    f"unsupported redis URL query parameter: {key!r}"
                )

    if parsed.scheme == "unix":
        if parsed.hostname:
            raise ValueError(
                "unix:// URL must use three slashes (unix:///path/to.sock)"
                f"; got authority {parsed.hostname!r}"
            )
        if not parsed.path:
            raise ValueError("unix:// URL must carry a socket path")
        endpoint.unix_path = parsed.path
        return endpoint

    endpoint.host = parsed.hostname or "127.0.0.1"
    endpoint.port = parsed.port or 6379
    db_path = parsed.path.lstrip("/")
    if db_path:
        try:
            endpoint.db = int(db_path)
        except ValueError as e:
            raise ValueError(
                f"invalid database index in redis URL: {db_path!r}"
            ) from e
    return endpoint


_ENGINE_PREFIX = "engine:"


# Atomic prune, byte-identical semantics to the reference's Lua script
# (redis.go:147-154): only if the request hash is empty (Redis removes
# hashes whose last field was HDELed) delete the engine->request mapping.
# Running HLEN + DEL server-side in one script closes the race where a
# concurrent add lands between the two and is then deleted wholesale.
_PRUNE_SCRIPT = (
    "local hashLen = redis.call('HLEN', KEYS[1])\n"
    "if hashLen == 0 then\n"
    "    redis.call('DEL', KEYS[2])\n"
    "    return 1\n"
    "end\n"
    "return 0"
)


class RedisIndex(Index):
    # The server outlives the indexer process and is shared by every
    # replica: startup recovery must never pipeline a possibly-stale
    # file snapshot back over fresher server state (persistence's
    # recover() gates on this; docs/persistence.md §6).  Explicit
    # dump/restore calls (parity tests, follower bootstrap, operator
    # backups) remain available.
    durable_backend = True

    def __init__(
        self,
        config: Optional[RedisIndexConfig] = None,
        client: Optional[RespClient] = None,
    ) -> None:
        self.config = config or RedisIndexConfig()
        if client is None:
            endpoint = parse_redis_url(self.config.address)
            ssl_context = None
            if endpoint.tls:
                ssl_context = ssl.create_default_context(
                    cafile=self.config.tls_ca_file
                )
                if self.config.tls_insecure_skip_verify:
                    ssl_context.check_hostname = False
                    ssl_context.verify_mode = ssl.CERT_NONE
            client = RespClient(
                endpoint=endpoint, ssl_context=ssl_context
            )
        self._client = client

    @staticmethod
    def _field(entry: PodEntry) -> str:
        return f"{entry.pod_identifier}@{entry.device_tier}"

    @staticmethod
    def _parse_field(field: bytes) -> Optional[PodEntry]:
        text = field.decode()
        pod, sep, tier = text.rpartition("@")
        if not sep:
            return None
        return PodEntry(pod_identifier=pod, device_tier=tier)

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")
        replies = self._client.pipeline(
            [("HKEYS", str(key)) for key in request_keys]
        )
        result: Dict[int, List[PodEntry]] = {}
        for key, fields in zip(request_keys, replies):
            if not fields:
                continue
            pods = []
            for field in fields:
                entry = self._parse_field(field)
                if entry is None:
                    continue
                if (
                    pod_identifier_set
                    and entry.pod_identifier not in pod_identifier_set
                ):
                    continue
                pods.append(entry)
            if pods:
                result[key] = pods
        return result

    def lookup_chain(
        self, request_keys: Sequence[int]
    ) -> List[Sequence[PodEntry]]:
        """Aligned per-key pod entries for the fast-lane scoring walk:
        ONE pipelined round trip of HKEYS for the whole chunk (the
        default adapter would pay the same trip via :meth:`lookup` but
        build a dict to tear down again), truncated at the first key
        with no resident pods."""
        if not request_keys:
            return []
        replies = self._client.pipeline(
            [("HKEYS", str(key)) for key in request_keys]
        )
        out: List[Sequence[PodEntry]] = []
        for fields in replies:
            pods = []
            if fields:
                for field in fields:
                    entry = self._parse_field(field)
                    if entry is not None:
                        pods.append(entry)
            if not pods:
                break
            out.append(pods)
        return out

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for add")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")
        commands: List[Sequence] = []
        for engine_key, request_key in zip(engine_keys, request_keys):
            hset: List = ["HSET", str(request_key)]
            for entry in entries:
                hset += [self._field(entry), "1"]
            commands.append(hset)
            commands.append(
                ("SET", f"{_ENGINE_PREFIX}{engine_key}", str(request_key))
            )
        self._client.pipeline(commands)

    def add_mappings(
        self, engine_keys: Sequence[int], request_keys: Sequence[int]
    ) -> None:
        """Publish engine->request mappings (one pipelined round trip)
        — the eager half of the kvevents batched-apply surface."""
        if not engine_keys:
            return
        self._client.pipeline(
            [
                ("SET", f"{_ENGINE_PREFIX}{ek}", str(rk))
                for ek, rk in zip(engine_keys, request_keys)
            ]
        )

    def add_entries_batch(
        self,
        items: Sequence[Tuple[Sequence[int], Sequence[PodEntry]]],
    ) -> None:
        """Admit ``(request_keys, entries)`` groups in ONE pipelined
        round trip (the deferred half of the batched-apply surface;
        mappings travel separately via :meth:`add_mappings`)."""
        commands: List[Sequence] = []
        for request_keys, entries in items:
            if not entries:
                continue
            fields: List = []
            for entry in entries:
                fields += [self._field(entry), "1"]
            for request_key in request_keys:
                commands.append(["HSET", str(request_key)] + fields)
        if commands:
            self._client.pipeline(commands)

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction")
        request_key_raw = self._client.execute(
            "GET", f"{_ENGINE_PREFIX}{engine_key}"
        )
        if request_key_raw is None:
            return
        request_key = request_key_raw.decode()
        hdel: List = ["HDEL", request_key]
        hdel += [self._field(entry) for entry in entries]
        # HDEL of the last field removes the hash itself server-side; the
        # Lua prune then atomically deletes the engine->request mapping
        # only if the hash is still empty, so an add racing in between is
        # never lost.
        self._client.pipeline(
            [
                hdel,
                (
                    "EVAL",
                    _PRUNE_SCRIPT,
                    "2",
                    request_key,
                    f"{_ENGINE_PREFIX}{engine_key}",
                ),
            ]
        )

    def get_request_key(self, engine_key: int) -> int:
        raw = self._client.execute("GET", f"{_ENGINE_PREFIX}{engine_key}")
        if raw is None:
            raise KeyError(f"engine key not found: {engine_key:#x}")
        return int(raw.decode())

    def dump_entries(
        self,
    ) -> Tuple[List[Tuple[int, List[PodEntry]]], List[Tuple[int, int]]]:
        """SCAN-walk the full schema into the standard dump shape.

        This replaced the long-documented no-op when the backend was
        promoted to replica duty (docs/replication.md): a shared-tier
        replica must answer the same dump/restore contract as the
        in-process backends so cluster parity tests, follower
        bootstrap, and the index-truth auditor see one surface.  The
        order is server iteration order — Redis tracks no recency, so
        a capacity-bounded restore into an LRU backend treats the dump
        as equally-recent (documented divergence from the LRU-first
        ordering of in-process dumps).  Foreign keys in a shared
        database (non-numeric names, wrong types) are skipped, never
        fatal.

        NOTE for persistence: snapshotting a durable server through
        the file layer yields a second copy that can go stale; prefer
        pointing recovery at the server itself (restore is idempotent
        either way — see docs/persistence.md).
        """
        block_entries: List[Tuple[int, List[PodEntry]]] = []
        engine_map: List[Tuple[int, int]] = []
        cursor = b"0"
        while True:
            reply = self._client.execute(
                "SCAN", cursor.decode(), "COUNT", "512"
            )
            cursor, keys = reply[0], reply[1]
            hash_keys: List[int] = []
            engine_keys: List[int] = []
            for key in keys:
                text = key.decode("utf-8", "replace")
                if text.startswith(_ENGINE_PREFIX):
                    try:
                        engine_keys.append(
                            int(text[len(_ENGINE_PREFIX):])
                        )
                    except ValueError:
                        continue  # foreign engine:* key
                else:
                    try:
                        hash_keys.append(int(text))
                    except ValueError:
                        continue  # foreign key
            if hash_keys:
                field_lists = self._client.pipeline(
                    [("HKEYS", str(key)) for key in hash_keys],
                    raise_on_error=False,
                )
                for key, fields in zip(hash_keys, field_lists):
                    if isinstance(fields, RespError) or not fields:
                        continue  # foreign type, or raced a removal
                    pods = []
                    for field in fields:
                        entry = self._parse_field(field)
                        if entry is not None:
                            pods.append(entry)
                    if pods:
                        block_entries.append((key, pods))
            if engine_keys:
                values = self._client.pipeline(
                    [
                        ("GET", f"{_ENGINE_PREFIX}{key}")
                        for key in engine_keys
                    ],
                    raise_on_error=False,
                )
                for engine_key, raw in zip(engine_keys, values):
                    if isinstance(raw, RespError) or raw is None:
                        continue
                    try:
                        engine_map.append((engine_key, int(raw)))
                    except ValueError:
                        continue  # foreign value
            if cursor == b"0":
                return block_entries, engine_map

    def restore_entries(self, block_entries, engine_map) -> int:
        """Pipelined re-admission of a dump (idempotent: HSET/SET of
        existing state is a no-op server-side); returns block keys
        carrying entries.  No capacity bound applies — the server's
        own maxmemory policy governs."""
        commands: List[Sequence] = []
        restored = 0
        for request_key, entries in block_entries:
            if not entries:
                continue
            hset: List = ["HSET", str(request_key)]
            for entry in entries:
                hset += [self._field(entry), "1"]
            commands.append(hset)
            restored += 1
        for engine_key, request_key in engine_map:
            commands.append(
                ("SET", f"{_ENGINE_PREFIX}{engine_key}", str(request_key))
            )
        if commands:
            self._client.pipeline(commands)
        return restored

    def purge_pod(self, pod_identifier: str) -> int:
        """SCAN-walk the request hashes, HDEL the pod's fields.

        Cursor iteration keeps the server responsive (no KEYS); real
        Redis auto-removes hashes whose last field is deleted, so
        emptied keys cannot break other pods' prefix chains.  Shared
        databases may hold foreign non-hash keys — their WRONGTYPE
        replies are tolerated per key, never fatal to the purge.
        """
        prefix = f"{pod_identifier}@".encode()
        removed = 0
        cursor = b"0"
        while True:
            reply = self._client.execute(
                "SCAN", cursor.decode(), "COUNT", "512"
            )
            cursor, keys = reply[0], reply[1]
            hash_keys = [
                key
                for key in keys
                if not key.startswith(_ENGINE_PREFIX.encode())
            ]
            if hash_keys:
                field_lists = self._client.pipeline(
                    [("HKEYS", key.decode()) for key in hash_keys],
                    raise_on_error=False,
                )
                hdels = []
                for key, fields in zip(hash_keys, field_lists):
                    if isinstance(fields, RespError):
                        continue  # foreign key of another type
                    victims = [
                        f for f in fields if f.startswith(prefix)
                    ]
                    if victims:
                        removed += len(victims)
                        hdels.append(
                            ["HDEL", key.decode()]
                            + [f.decode() for f in victims]
                        )
                if hdels:
                    self._client.pipeline(hdels)
            if cursor == b"0":
                return removed
