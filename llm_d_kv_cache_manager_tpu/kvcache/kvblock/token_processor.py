"""Token-sequence -> chained KV-block-hash pipeline.

This is the cross-system contract at the heart of KV-aware routing: the
indexer must reproduce, bit for bit, the block hashes that engine pods
compute for their paged KV cache, so that a prompt tokenized centrally maps
onto the same chain of block keys the fleet advertises in KVEvents.

Semantics match the reference indexer (pkg/kvcache/kvblock/
token_processor.go:75-159) and, transitively, vLLM's chunked token database:

* ``init_hash   = FNV-64a(hash_seed_bytes)`` — the seed must equal the
  fleet's ``PYTHONHASHSEED`` (docs/configuration.md:481).
* ``model_init  = FNV-64a(CBOR([init_hash, null, model_name]))``.
* per chunk of ``block_size`` tokens (**no partial blocks**):
  ``h_i = FNV-64a(CBOR([h_{i-1}, chunk_tokens, null]))``.
* an explicit ``parent_key`` continues an existing chain (used by the event
  write path to chain off a stored parent block).

The hot loop optionally runs in the native C++ engine (see
``llm_d_kv_cache_manager_tpu.native``); the pure-Python path is the
always-available reference implementation and the parity oracle for tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Protocol, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    encode_chunk_payload,
    encode_hash_payload,
)

# Sentinel for "no parent": hash chains start from the per-model init hash.
EMPTY_BLOCK_HASH = 0

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF

# Default number of tokens per KV block; matches vLLM's default block size
# (reference: token_processor.go:29-31).
DEFAULT_BLOCK_SIZE = 16


def fnv1a_64(data) -> int:
    """64-bit FNV-1a over ``data`` (bytes-like).

    ``bytes`` and ``bytearray`` iterate as ints natively, so the hash
    hot loop's working ``bytearray`` passes straight through; anything
    else (e.g. ``memoryview``) is copied to ``bytes`` first — iterating
    a view costs more than the copy it avoids.
    """
    if type(data) not in (bytes, bytearray):
        data = bytes(data)
    h = _FNV64_OFFSET
    prime = _FNV64_PRIME
    mask = _MASK64
    for byte in data:
        h = ((h ^ byte) * prime) & mask
    return h


@dataclass
class TokenProcessorConfig:
    """Block-hash chain parameters.

    ``hash_seed`` must be aligned with the serving fleet's
    ``PYTHONHASHSEED`` — a mismatch silently zeroes the cache-hit rate.
    """

    block_size: int = DEFAULT_BLOCK_SIZE
    hash_seed: str = ""


class TokenProcessor(Protocol):
    """Converts token sequences into chained KV-block keys."""

    def tokens_to_kv_block_keys(
        self, parent_key: int, tokens: Sequence[int], model_name: str
    ) -> List[int]:
        ...


class ChunkedTokenDatabase:
    """Chunked, chained block hashing compatible with the fleet's engines."""

    def __init__(
        self,
        config: Optional[TokenProcessorConfig] = None,
        use_native: bool = True,
    ) -> None:
        self.config = config or TokenProcessorConfig()
        if self.config.block_size <= 0:
            raise ValueError(
                f"block_size must be positive, got {self.config.block_size}"
            )
        self._init_hash = fnv1a_64(self.config.hash_seed.encode("utf-8"))
        # Per-model chain roots are deterministic; memoize them.
        self._model_init_cache: dict = {}
        self._native_chain = None
        if use_native:
            try:
                from llm_d_kv_cache_manager_tpu.native import get_library
                from llm_d_kv_cache_manager_tpu.native.engine import (
                    native_hash_chain,
                )

                # Trigger the (possibly slow) first build here at
                # construction, not inside the first scoring request.
                if get_library() is not None:
                    self._native_chain = native_hash_chain
            except Exception:  # no compiler / import issue: pure Python
                self._native_chain = None

    @property
    def block_size(self) -> int:
        return self.config.block_size

    @property
    def key_space(self) -> Tuple[int, int]:
        """Identity of this processor's hash space: two chains agree on
        every block key iff their ``(seed hash, block size)`` pairs (and
        the model name, carried separately) agree.  Memoization caches
        (the prefix store's block-key records) key on this so a config
        change can never replay keys from a different space."""
        return (self._init_hash, self.config.block_size)

    def chunk_hash(
        self, parent: int, tokens: Sequence[int] | None, extra=None
    ) -> int:
        """One link of the chain: FNV-64a over the canonical CBOR payload."""
        if extra is None and tokens is not None:
            # The per-chunk shape [parent, tokens, null]: precomputed
            # framing, no bytes() copy (parity pinned against the
            # generic encoder by the golden-chain tests).
            return fnv1a_64(encode_chunk_payload(parent, tokens))
        return fnv1a_64(encode_hash_payload(parent, tokens, extra))

    def model_init_hash(self, model_name: str) -> int:
        cached = self._model_init_cache.get(model_name)
        if cached is None:
            cached = self.chunk_hash(self._init_hash, None, model_name)
            self._model_init_cache[model_name] = cached
        return cached

    def tokens_to_kv_block_keys(
        self, parent_key: int, tokens: Sequence[int], model_name: str
    ) -> List[int]:
        """Hash ``tokens`` into a chain of block keys.

        Only full ``block_size`` chunks are hashed; a trailing partial block
        produces no key.  ``parent_key == EMPTY_BLOCK_HASH`` starts a fresh
        chain rooted at the per-model init hash.
        """
        if parent_key != EMPTY_BLOCK_HASH:
            prefix = parent_key & _MASK64
        else:
            prefix = self.model_init_hash(model_name)

        size = self.config.block_size
        if self._native_chain is not None:
            keys = self._native_chain(prefix, tokens, size)
            if keys is not None:
                return keys

        n_chunks = len(tokens) // size
        keys = []
        for i in range(n_chunks):
            chunk = tokens[i * size : (i + 1) * size]
            prefix = self.chunk_hash(prefix, chunk, None)
            keys.append(prefix)
        return keys

    def extend_block_keys(
        self, parent_key: int, tokens: Sequence[int], model_name: str
    ) -> List[int]:
        """Resume a block-key chain off ``parent_key``.

        The memoization fast lane's suffix path: block keys are pure
        functions of ``(seed, model, block size, token chain)``, so a
        multi-turn conversation whose prefix keys are already known
        only hashes its new suffix — ``tokens`` must start at the first
        token NOT covered by a full block of the parent chain (i.e. at
        offset ``len(prefix_keys) * block_size`` of the full token
        list).  ``parent_key == EMPTY_BLOCK_HASH`` starts a fresh chain
        (identical to :meth:`tokens_to_kv_block_keys`); resumed chains
        are bit-identical to fresh full-chain hashing (pinned by the
        property tests in tests/test_read_path_fastlane.py).
        """
        return self.tokens_to_kv_block_keys(parent_key, tokens, model_name)


def engine_hash_to_uint64(raw) -> int:
    """Normalize an engine-reported block hash to uint64.

    Engines may report block hashes as integers (legacy) or as byte strings
    (e.g. vLLM's ``sha256_cbor`` digests).  Byte strings use the last 8
    bytes big-endian; shorter strings are zero-padded on the left
    (reference: pkg/kvevents/pool.go:336-363).
    """
    if isinstance(raw, bool):
        raise TypeError("boolean is not a valid block hash")
    if isinstance(raw, int):
        return raw & _MASK64
    if isinstance(raw, (bytes, bytearray)):
        if len(raw) == 0:
            raise ValueError("empty block-hash byte string")
        tail = bytes(raw[-8:])
        return int.from_bytes(tail, "big")
    raise TypeError(f"unsupported block-hash type: {type(raw)!r}")
