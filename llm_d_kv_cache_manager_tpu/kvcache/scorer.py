"""Pod scoring: longest consecutive resident prefix, tier-weighted.

Semantics follow the reference scorer (pkg/kvcache/kvblock_scorer.go:108-151):
starting from block 0, a pod accrues score while it appears for every
consecutive block key; the per-block increment is the maximum tier weight
among the pod's entries for that key.  Pods missing from block 0 score 0.

TPU tier weights default to HBM > host DRAM > shared storage, with the
GPU-era names accepted as aliases so mixed fleets and recorded event streams
keep scoring correctly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry

LONGEST_PREFIX_MATCH = "longest-prefix-match"


@dataclass(frozen=True)
class TierConfig:
    """One device tier and its scoring weight."""

    name: str
    weight: float


def default_tier_configs() -> List[TierConfig]:
    """TPU memory hierarchy weights (capability parity: pkg/kvcache/
    backend.go:19-31, which weighted gpu=1.0 > cpu=0.8)."""
    return [
        TierConfig("hbm", 1.0),
        TierConfig("host", 0.8),
        TierConfig("shared_storage", 0.5),
        # GPU-era aliases for wire compatibility with existing fleets.
        TierConfig("gpu", 1.0),
        TierConfig("cpu", 0.8),
    ]


@dataclass
class ScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    tier_configs: List[TierConfig] = field(default_factory=default_tier_configs)


class LongestPrefixScorer:
    def __init__(self, tier_weights: Mapping[str, float]) -> None:
        self.tier_weights = dict(tier_weights)

    def _best_entry(
        self, entries: Sequence[PodEntry], pod_id: str
    ) -> tuple:
        """(max weight, its tier) for one pod's entries on one block.
        Single source of tier-weight resolution: ``score`` and
        ``explain`` both resolve through here, so they cannot drift."""
        best, tier = 0.0, None
        for entry in entries:
            if entry.pod_identifier != pod_id:
                continue
            weight = self.tier_weights.get(entry.device_tier, 1.0)
            if tier is None or weight > best:
                best, tier = weight, entry.device_tier
        return best, tier

    def _max_weight(self, entries: Sequence[PodEntry], pod_id: str) -> float:
        return self._best_entry(entries, pod_id)[0]

    def score(
        self,
        keys: Sequence[int],
        key_to_pods: Mapping[int, Sequence[PodEntry]],
    ) -> Dict[str, float]:
        if not keys:
            return {}

        first_pods = key_to_pods.get(keys[0], ())
        active = {p.pod_identifier for p in first_pods}
        scores: Dict[str, float] = {
            pod: self._max_weight(first_pods, pod) for pod in active
        }

        for key in keys[1:]:
            if not active:
                break
            pods = key_to_pods.get(key, ())
            active &= {p.pod_identifier for p in pods}
            for pod in active:
                scores[pod] += self._max_weight(pods, pod)
        return scores

    def explain(
        self,
        keys: Sequence[int],
        key_to_pods: Mapping[int, Sequence[PodEntry]],
    ) -> Dict[str, dict]:
        """Score with per-pod provenance (the ``explain=1`` surface).

        For each pod appearing on block 0: its score (identical to
        ``score()``), how many consecutive blocks matched, the block
        index where its prefix chain broke (``None`` when it survived
        every looked-up block), and per-tier counts of the blocks that
        scored (which memory tier each hit came from).  Pods missing
        from block 0 score 0 in ``score()`` and are omitted here, same
        as there.
        """
        if not keys:
            return {}

        first_pods = key_to_pods.get(keys[0], ())
        active = {p.pod_identifier for p in first_pods}
        result: Dict[str, dict] = {}
        for pod in active:
            weight, tier = self._best_entry(first_pods, pod)
            result[pod] = {
                "score": weight,
                "blocks_matched": 1,
                "break_index": None,
                "tiers": {tier: 1},
            }

        for i, key in enumerate(keys[1:], start=1):
            if not active:
                break
            pods = key_to_pods.get(key, ())
            present = {p.pod_identifier for p in pods}
            for pod in active - present:
                result[pod]["break_index"] = i
            active &= present
            for pod in active:
                weight, tier = self._best_entry(pods, pod)
                entry = result[pod]
                entry["score"] += weight
                entry["blocks_matched"] += 1
                entry["tiers"][tier] = entry["tiers"].get(tier, 0) + 1
        return result


def new_scorer(config: ScorerConfig) -> LongestPrefixScorer:
    if config.scoring_strategy != LONGEST_PREFIX_MATCH:
        raise ValueError(
            f"unsupported scoring strategy: {config.scoring_strategy}"
        )
    return LongestPrefixScorer(
        {tier.name: tier.weight for tier in config.tier_configs}
    )
