"""Pod scoring: longest consecutive resident prefix, tier-weighted.

Semantics follow the reference scorer (pkg/kvcache/kvblock_scorer.go:108-151):
starting from block 0, a pod accrues score while it appears for every
consecutive block key; the per-block increment is the maximum tier weight
among the pod's entries for that key.  Pods missing from block 0 score 0.

TPU tier weights default to HBM > host DRAM > shared storage, with the
GPU-era names accepted as aliases so mixed fleets and recorded event streams
keep scoring correctly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("kvcache.scorer")

LONGEST_PREFIX_MATCH = "longest-prefix-match"

# Tiers absent from the weight table score this (the most-valuable
# weight): unknown > known keeps new tier strings from zeroing scores
# on old deployments, at the cost of over-valuing them until the
# deployment learns the tier.  Logged once per unknown tier name —
# demotion events introduce new tier strings to fleets whose scorer
# config predates them (docs/configuration.md §Scoring).
UNKNOWN_TIER_WEIGHT = 1.0


@dataclass(frozen=True)
class TierConfig:
    """One device tier and its scoring weight."""

    name: str
    weight: float


def default_tier_configs() -> List[TierConfig]:
    """TPU memory hierarchy weights (capability parity: pkg/kvcache/
    backend.go:19-31, which weighted gpu=1.0 > cpu=0.8)."""
    return [
        TierConfig("hbm", 1.0),
        TierConfig("host", 0.8),
        TierConfig("shared_storage", 0.5),
        # GPU-era aliases for wire compatibility with existing fleets.
        TierConfig("gpu", 1.0),
        TierConfig("cpu", 0.8),
    ]


@dataclass
class ScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    tier_configs: List[TierConfig] = field(default_factory=default_tier_configs)


class ScoreChain:
    """Resumable longest-prefix scoring state (the fast lane's chunked
    drive): ``scores`` accumulates per-pod totals, ``active`` is the
    set of pods still alive on every consecutive block so far (``None``
    until block 0 has been fed).

    ``matched_blocks`` counts blocks on which at least one candidate
    accrued — i.e. the best pod's consecutive matched-block count, the
    analytics ledger's attribution input; always tracked (one integer
    increment per block).  Two opt-in provenance modes cost only when
    requested: ``track_tiers`` splits matched blocks by the best
    resident tier per block (the ledger's per-tier hit split) and
    ``track_deaths`` records each pod's chain-break index (the span
    attrs a traced request carries, matching ``explain``'s
    ``break_index`` exactly — both are pinned by property tests)."""

    __slots__ = ("scores", "active", "matched_blocks", "position",
                 "tier_counts", "deaths")

    def __init__(
        self, track_tiers: bool = False, track_deaths: bool = False
    ) -> None:
        self.scores: Dict[str, float] = {}
        self.active = None  # type: ignore[assignment]
        self.matched_blocks = 0
        self.position = 0  # blocks examined (including a killing block)
        self.tier_counts: Optional[Dict[str, int]] = (
            {} if track_tiers else None
        )
        self.deaths: Optional[Dict[str, int]] = (
            {} if track_deaths else None
        )

    @property
    def alive(self) -> bool:
        """True while feeding more blocks could still change scores."""
        return self.active is None or bool(self.active)

    def provenance(self) -> Dict[str, dict]:
        """Per-pod ``{blocks_matched, break_index}`` for the walked
        chain (requires ``track_deaths``): a pod that broke at block i
        matched blocks 0..i-1; survivors matched every examined block
        and carry ``break_index None`` — the same semantics as
        ``LongestPrefixScorer.explain``."""
        deaths = self.deaths if self.deaths is not None else {}
        return {
            pod: {
                "blocks_matched": deaths.get(pod, self.matched_blocks),
                "break_index": deaths.get(pod),
            }
            for pod in self.scores
        }


class LongestPrefixScorer:
    def __init__(self, tier_weights: Mapping[str, float]) -> None:
        self.tier_weights = dict(tier_weights)
        # Canonical tier name per weight, first declaration wins: with
        # the default table both "hbm" and its "gpu" alias weigh 1.0,
        # and the ledger's per-tier split normalizes aliases to the
        # canonical TPU names.  Unknown tiers resolve through the same
        # 1.0 default the scoring loops use.
        self._weight_to_tier: Dict[float, str] = {}
        for name, weight in self.tier_weights.items():
            self._weight_to_tier.setdefault(weight, name)
        self._default_tier = self._weight_to_tier.get(1.0, "other")
        # Unknown tiers warn ONCE per tier name (set adds are
        # GIL-atomic; a racy duplicate log is harmless).
        self._warned_tiers: set = set()
        # Per-snapshot weight resolution, keyed on entry-tuple IDENTITY
        # (the in-memory index hands out one cached snapshot tuple per
        # pod cache until it mutates, so steady-state requests re-see
        # the same objects).  Entries hold a strong ref to the keyed
        # object and validate with ``is`` before use, so id() reuse
        # after GC can never alias.  Bounded by wholesale clear; benign
        # under concurrent readers (single-key dict ops only).
        self._resolve_cache: Dict[int, tuple] = {}

    _RESOLVE_CACHE_MAX = 8192

    def _resolve(self, pods: Sequence[PodEntry]) -> Dict[str, float]:
        """{pod: max tier weight} over one block's entries, memoized
        per snapshot identity.  Only TUPLES are cached: the in-memory
        index hands out stable snapshot tuples that recur across
        requests, while dict-adapted backends produce fresh lists per
        request — caching those would churn the table (and pin dead
        lists) for zero hits."""
        is_tuple = type(pods) is tuple
        if is_tuple:
            cached = self._resolve_cache.get(id(pods))
            if cached is not None and cached[0] is pods:
                return cached[1]
        weights = self.tier_weights
        best: Dict[str, float] = {}
        for entry in pods:
            pod = entry.pod_identifier
            weight = weights.get(entry.device_tier)
            if weight is None:
                weight = self._unknown_tier_weight(entry.device_tier)
            prev = best.get(pod)
            if prev is None or weight > prev:
                best[pod] = weight
        if is_tuple:
            cache = self._resolve_cache
            if len(cache) >= self._RESOLVE_CACHE_MAX:
                cache.clear()
            cache[id(pods)] = (pods, best)
        return best

    def _best_entry(
        self, entries: Sequence[PodEntry], pod_id: str
    ) -> tuple:
        """(max weight, its tier) for one pod's entries on one block.
        ``explain`` resolves tiers through here; ``score``/``advance``
        resolve through ``_resolve`` — both route unknown tiers
        through the same warn-once ``_unknown_tier_weight`` fallback,
        and the explain≡score property test pins the two against
        drifting."""
        best, tier = 0.0, None
        for entry in entries:
            if entry.pod_identifier != pod_id:
                continue
            weight = self.tier_weights.get(entry.device_tier)
            if weight is None:
                weight = self._unknown_tier_weight(entry.device_tier)
            if tier is None or weight > best:
                best, tier = weight, entry.device_tier
        return best, tier

    def _unknown_tier_weight(self, tier: str) -> float:
        """Fallback for tiers absent from the weight table: score
        UNKNOWN_TIER_WEIGHT, logging once per tier name so a fleet
        rollout that introduces a new medium string is visible in the
        indexer's logs instead of silently shifting scores."""
        if tier not in self._warned_tiers:
            self._warned_tiers.add(tier)
            logger.warning(
                "unknown device tier %r in index entries: scoring with "
                "fallback weight %s; add it to ScorerConfig.tier_configs "
                "to weight it deliberately (docs/configuration.md)",
                tier,
                UNKNOWN_TIER_WEIGHT,
            )
        return UNKNOWN_TIER_WEIGHT

    def begin(
        self, track_tiers: bool = False, track_deaths: bool = False
    ) -> ScoreChain:
        return ScoreChain(
            track_tiers=track_tiers, track_deaths=track_deaths
        )

    def advance(
        self,
        chain: ScoreChain,
        pods_per_key: Sequence[Sequence[PodEntry]],
        pod_identifier_set=None,
    ) -> bool:
        """Feed the next consecutive blocks' pod entries into ``chain``.

        ``pods_per_key[i]`` holds the entries for the chain's next
        block ``i`` (in order).  Entries outside ``pod_identifier_set``
        (when given) are ignored without allocating filtered copies.
        Returns False once the prefix chain is dead for every candidate
        pod — the caller can stop hashing and looking up further
        blocks; feeding more after that is a no-op.
        """
        scores = chain.scores
        active = chain.active
        resolve = self._resolve
        tier_counts = chain.tier_counts
        deaths = chain.deaths
        weight_to_tier = self._weight_to_tier
        default_tier = self._default_tier
        start = 0
        if active is None:
            if not pods_per_key:
                return True
            # Block 0 defines the candidate set.  The pod filter only
            # needs applying here: later blocks intersect with
            # ``active``, which is already a subset of the filter.
            pods = pods_per_key[0]
            best = resolve(pods) if pods else {}
            if pod_identifier_set is not None and best:
                best = {
                    pod: weight
                    for pod, weight in best.items()
                    if pod in pod_identifier_set
                }
            scores.update(best)
            chain.active = active = set(best)
            chain.position = 1
            if not active:
                return False
            chain.matched_blocks = 1
            if tier_counts is not None:
                tier = weight_to_tier.get(
                    max(best.values()), default_tier
                )
                tier_counts[tier] = tier_counts.get(tier, 0) + 1
            start = 1
        elif not active:
            return False
        for index in range(start, len(pods_per_key)):
            pods = pods_per_key[index]
            position = chain.position
            chain.position = position + 1
            if not pods:
                if deaths is not None:
                    for pod in active:
                        deaths[pod] = position
                active.clear()
                return False
            best = resolve(pods)
            best_keys = best.keys()
            if best_keys == active:
                # Steady state: every active pod present — accrue.
                if tier_counts is None:
                    for pod, weight in best.items():
                        scores[pod] += weight
                else:
                    # Fused max: the accrue loop already visits every
                    # weight, so tier attribution costs one compare per
                    # pod, not a second pass.
                    best_weight = 0.0
                    for pod, weight in best.items():
                        scores[pod] += weight
                        if weight > best_weight:
                            best_weight = weight
                    tier = weight_to_tier.get(best_weight, default_tier)
                    tier_counts[tier] = tier_counts.get(tier, 0) + 1
                chain.matched_blocks += 1
                continue
            survivors = active & best_keys
            if deaths is not None:
                for pod in active - survivors:
                    deaths[pod] = position
            chain.active = active = survivors
            if not survivors:
                return False
            if tier_counts is None:
                for pod in survivors:
                    scores[pod] += best[pod]
            else:
                best_weight = 0.0
                for pod in survivors:
                    weight = best[pod]
                    scores[pod] += weight
                    if weight > best_weight:
                        best_weight = weight
                tier = weight_to_tier.get(best_weight, default_tier)
                tier_counts[tier] = tier_counts.get(tier, 0) + 1
            chain.matched_blocks += 1
        return True

    def score(
        self,
        keys: Sequence[int],
        key_to_pods: Mapping[int, Sequence[PodEntry]],
    ) -> Dict[str, float]:
        if not keys:
            return {}
        chain = self.begin()
        self.advance(
            chain, [key_to_pods.get(key, ()) for key in keys]
        )
        return chain.scores

    def explain(
        self,
        keys: Sequence[int],
        key_to_pods: Mapping[int, Sequence[PodEntry]],
    ) -> Dict[str, dict]:
        """Score with per-pod provenance (the ``explain=1`` surface).

        For each pod appearing on block 0: its score (identical to
        ``score()``), how many consecutive blocks matched, the block
        index where its prefix chain broke (``None`` when it survived
        every looked-up block), and per-tier counts of the blocks that
        scored (which memory tier each hit came from).  Pods missing
        from block 0 score 0 in ``score()`` and are omitted here, same
        as there.
        """
        if not keys:
            return {}

        first_pods = key_to_pods.get(keys[0], ())
        active = {p.pod_identifier for p in first_pods}
        result: Dict[str, dict] = {}
        for pod in active:
            weight, tier = self._best_entry(first_pods, pod)
            result[pod] = {
                "score": weight,
                "blocks_matched": 1,
                "break_index": None,
                "tiers": {tier: 1},
            }

        for i, key in enumerate(keys[1:], start=1):
            if not active:
                break
            pods = key_to_pods.get(key, ())
            present = {p.pod_identifier for p in pods}
            for pod in active - present:
                result[pod]["break_index"] = i
            active &= present
            for pod in active:
                weight, tier = self._best_entry(pods, pod)
                entry = result[pod]
                entry["score"] += weight
                entry["blocks_matched"] += 1
                entry["tiers"][tier] = entry["tiers"].get(tier, 0) + 1
        return result


def new_scorer(config: ScorerConfig) -> LongestPrefixScorer:
    if config.scoring_strategy != LONGEST_PREFIX_MATCH:
        raise ValueError(
            f"unsupported scoring strategy: {config.scoring_strategy}"
        )
    return LongestPrefixScorer(
        {tier.name: tier.weight for tier in config.tier_configs}
    )
