"""Pod scoring: longest consecutive resident prefix, tier-weighted.

Semantics follow the reference scorer (pkg/kvcache/kvblock_scorer.go:108-151):
starting from block 0, a pod accrues score while it appears for every
consecutive block key; the per-block increment is the maximum tier weight
among the pod's entries for that key.  Pods missing from block 0 score 0.

TPU tier weights default to HBM > host DRAM > shared storage, with the
GPU-era names accepted as aliases so mixed fleets and recorded event streams
keep scoring correctly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry

LONGEST_PREFIX_MATCH = "longest-prefix-match"


@dataclass(frozen=True)
class TierConfig:
    """One device tier and its scoring weight."""

    name: str
    weight: float


def default_tier_configs() -> List[TierConfig]:
    """TPU memory hierarchy weights (capability parity: pkg/kvcache/
    backend.go:19-31, which weighted gpu=1.0 > cpu=0.8)."""
    return [
        TierConfig("hbm", 1.0),
        TierConfig("host", 0.8),
        TierConfig("shared_storage", 0.5),
        # GPU-era aliases for wire compatibility with existing fleets.
        TierConfig("gpu", 1.0),
        TierConfig("cpu", 0.8),
    ]


@dataclass
class ScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    tier_configs: List[TierConfig] = field(default_factory=default_tier_configs)


class LongestPrefixScorer:
    def __init__(self, tier_weights: Mapping[str, float]) -> None:
        self.tier_weights = dict(tier_weights)

    def _max_weight(self, entries: Sequence[PodEntry], pod_id: str) -> float:
        best = 0.0
        for entry in entries:
            if entry.pod_identifier != pod_id:
                continue
            weight = self.tier_weights.get(entry.device_tier, 1.0)
            if weight > best:
                best = weight
        return best

    def score(
        self,
        keys: Sequence[int],
        key_to_pods: Mapping[int, Sequence[PodEntry]],
    ) -> Dict[str, float]:
        if not keys:
            return {}

        first_pods = key_to_pods.get(keys[0], ())
        active = {p.pod_identifier for p in first_pods}
        scores: Dict[str, float] = {
            pod: self._max_weight(first_pods, pod) for pod in active
        }

        for key in keys[1:]:
            if not active:
                break
            pods = key_to_pods.get(key, ())
            active &= {p.pod_identifier for p in pods}
            for pod in active:
                scores[pod] += self._max_weight(pods, pod)
        return scores


def new_scorer(config: ScorerConfig) -> LongestPrefixScorer:
    if config.scoring_strategy != LONGEST_PREFIX_MATCH:
        raise ValueError(
            f"unsupported scoring strategy: {config.scoring_strategy}"
        )
    return LongestPrefixScorer(
        {tier.name: tier.weight for tier in config.tier_configs}
    )
