"""Pod scoring: longest consecutive resident prefix, tier-weighted.

Semantics follow the reference scorer (pkg/kvcache/kvblock_scorer.go:108-151):
starting from block 0, a pod accrues score while it appears for every
consecutive block key; the per-block increment is the maximum tier weight
among the pod's entries for that key.  Pods missing from block 0 score 0.

TPU tier weights default to HBM > host DRAM > shared storage, with the
GPU-era names accepted as aliases so mixed fleets and recorded event streams
keep scoring correctly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry

LONGEST_PREFIX_MATCH = "longest-prefix-match"


@dataclass(frozen=True)
class TierConfig:
    """One device tier and its scoring weight."""

    name: str
    weight: float


def default_tier_configs() -> List[TierConfig]:
    """TPU memory hierarchy weights (capability parity: pkg/kvcache/
    backend.go:19-31, which weighted gpu=1.0 > cpu=0.8)."""
    return [
        TierConfig("hbm", 1.0),
        TierConfig("host", 0.8),
        TierConfig("shared_storage", 0.5),
        # GPU-era aliases for wire compatibility with existing fleets.
        TierConfig("gpu", 1.0),
        TierConfig("cpu", 0.8),
    ]


@dataclass
class ScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    tier_configs: List[TierConfig] = field(default_factory=default_tier_configs)


class ScoreChain:
    """Resumable longest-prefix scoring state (the fast lane's chunked
    drive): ``scores`` accumulates per-pod totals, ``active`` is the
    set of pods still alive on every consecutive block so far (``None``
    until block 0 has been fed)."""

    __slots__ = ("scores", "active")

    def __init__(self) -> None:
        self.scores: Dict[str, float] = {}
        self.active = None  # type: ignore[assignment]

    @property
    def alive(self) -> bool:
        """True while feeding more blocks could still change scores."""
        return self.active is None or bool(self.active)


class LongestPrefixScorer:
    def __init__(self, tier_weights: Mapping[str, float]) -> None:
        self.tier_weights = dict(tier_weights)
        # Per-snapshot weight resolution, keyed on entry-tuple IDENTITY
        # (the in-memory index hands out one cached snapshot tuple per
        # pod cache until it mutates, so steady-state requests re-see
        # the same objects).  Entries hold a strong ref to the keyed
        # object and validate with ``is`` before use, so id() reuse
        # after GC can never alias.  Bounded by wholesale clear; benign
        # under concurrent readers (single-key dict ops only).
        self._resolve_cache: Dict[int, tuple] = {}

    _RESOLVE_CACHE_MAX = 8192

    def _resolve(self, pods: Sequence[PodEntry]) -> Dict[str, float]:
        """{pod: max tier weight} over one block's entries, memoized
        per snapshot identity.  Only TUPLES are cached: the in-memory
        index hands out stable snapshot tuples that recur across
        requests, while dict-adapted backends produce fresh lists per
        request — caching those would churn the table (and pin dead
        lists) for zero hits."""
        is_tuple = type(pods) is tuple
        if is_tuple:
            cached = self._resolve_cache.get(id(pods))
            if cached is not None and cached[0] is pods:
                return cached[1]
        weights = self.tier_weights
        best: Dict[str, float] = {}
        for entry in pods:
            pod = entry.pod_identifier
            weight = weights.get(entry.device_tier, 1.0)
            prev = best.get(pod)
            if prev is None or weight > prev:
                best[pod] = weight
        if is_tuple:
            cache = self._resolve_cache
            if len(cache) >= self._RESOLVE_CACHE_MAX:
                cache.clear()
            cache[id(pods)] = (pods, best)
        return best

    def _best_entry(
        self, entries: Sequence[PodEntry], pod_id: str
    ) -> tuple:
        """(max weight, its tier) for one pod's entries on one block.
        ``explain`` resolves tiers through here; ``score``/``advance``
        inline the same ``tier_weights.get(tier, 1.0)`` resolution on
        the hot loop — the explain≡score property test pins the two
        against drifting."""
        best, tier = 0.0, None
        for entry in entries:
            if entry.pod_identifier != pod_id:
                continue
            weight = self.tier_weights.get(entry.device_tier, 1.0)
            if tier is None or weight > best:
                best, tier = weight, entry.device_tier
        return best, tier

    def begin(self) -> ScoreChain:
        return ScoreChain()

    def advance(
        self,
        chain: ScoreChain,
        pods_per_key: Sequence[Sequence[PodEntry]],
        pod_identifier_set=None,
    ) -> bool:
        """Feed the next consecutive blocks' pod entries into ``chain``.

        ``pods_per_key[i]`` holds the entries for the chain's next
        block ``i`` (in order).  Entries outside ``pod_identifier_set``
        (when given) are ignored without allocating filtered copies.
        Returns False once the prefix chain is dead for every candidate
        pod — the caller can stop hashing and looking up further
        blocks; feeding more after that is a no-op.
        """
        scores = chain.scores
        active = chain.active
        resolve = self._resolve
        start = 0
        if active is None:
            if not pods_per_key:
                return True
            # Block 0 defines the candidate set.  The pod filter only
            # needs applying here: later blocks intersect with
            # ``active``, which is already a subset of the filter.
            pods = pods_per_key[0]
            best = resolve(pods) if pods else {}
            if pod_identifier_set is not None and best:
                best = {
                    pod: weight
                    for pod, weight in best.items()
                    if pod in pod_identifier_set
                }
            scores.update(best)
            chain.active = active = set(best)
            if not active:
                return False
            start = 1
        elif not active:
            return False
        for index in range(start, len(pods_per_key)):
            pods = pods_per_key[index]
            if not pods:
                active.clear()
                return False
            best = resolve(pods)
            best_keys = best.keys()
            if best_keys == active:
                # Steady state: every active pod present — accrue.
                for pod, weight in best.items():
                    scores[pod] += weight
                continue
            survivors = active & best_keys
            chain.active = active = survivors
            if not survivors:
                return False
            for pod in survivors:
                scores[pod] += best[pod]
        return True

    def score(
        self,
        keys: Sequence[int],
        key_to_pods: Mapping[int, Sequence[PodEntry]],
    ) -> Dict[str, float]:
        if not keys:
            return {}
        chain = self.begin()
        self.advance(
            chain, [key_to_pods.get(key, ()) for key in keys]
        )
        return chain.scores

    def explain(
        self,
        keys: Sequence[int],
        key_to_pods: Mapping[int, Sequence[PodEntry]],
    ) -> Dict[str, dict]:
        """Score with per-pod provenance (the ``explain=1`` surface).

        For each pod appearing on block 0: its score (identical to
        ``score()``), how many consecutive blocks matched, the block
        index where its prefix chain broke (``None`` when it survived
        every looked-up block), and per-tier counts of the blocks that
        scored (which memory tier each hit came from).  Pods missing
        from block 0 score 0 in ``score()`` and are omitted here, same
        as there.
        """
        if not keys:
            return {}

        first_pods = key_to_pods.get(keys[0], ())
        active = {p.pod_identifier for p in first_pods}
        result: Dict[str, dict] = {}
        for pod in active:
            weight, tier = self._best_entry(first_pods, pod)
            result[pod] = {
                "score": weight,
                "blocks_matched": 1,
                "break_index": None,
                "tiers": {tier: 1},
            }

        for i, key in enumerate(keys[1:], start=1):
            if not active:
                break
            pods = key_to_pods.get(key, ())
            present = {p.pod_identifier for p in pods}
            for pod in active - present:
                result[pod]["break_index"] = i
            active &= present
            for pod in active:
                weight, tier = self._best_entry(pods, pod)
                entry = result[pod]
                entry["score"] += weight
                entry["blocks_matched"] += 1
                entry["tiers"][tier] = entry["tiers"].get(tier, 0) + 1
        return result


def new_scorer(config: ScorerConfig) -> LongestPrefixScorer:
    if config.scoring_strategy != LONGEST_PREFIX_MATCH:
        raise ValueError(
            f"unsupported scoring strategy: {config.scoring_strategy}"
        )
    return LongestPrefixScorer(
        {tier.name: tier.weight for tier in config.tier_configs}
    )
