from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: F401
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    EventBatch,
    decode_event,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: F401
    Message,
    Pool,
    PoolConfig,
    ResyncJob,
)
from llm_d_kv_cache_manager_tpu.kvevents.poller import (  # noqa: F401
    ChannelConfig,
    PollerPool,
    PollerPoolConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.resync import (  # noqa: F401
    CallableInventorySource,
    EmptyInventorySource,
    InventoryBlock,
    InventorySource,
    PodInventory,
    ResyncConfig,
    ResyncManager,
)
from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (  # noqa: F401
    SubscriberManager,
)
