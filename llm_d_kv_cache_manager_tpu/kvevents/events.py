"""KVEvents wire model: msgpack array-encoded structs, tagged unions.

Wire compatibility with vLLM's KV-event stream is a hard requirement — the
fleet publishes these, the indexer only listens.  Layout (reference:
pkg/kvevents/events.go):

* ``EventBatch``    -> ``[ts, [raw_event, ...], data_parallel_rank?]``
* ``BlockStored``   -> ``["BlockStored", block_hashes, parent_block_hash,
                         token_ids, block_size, lora_id?, medium?,
                         lora_name?]``
* ``BlockRemoved``  -> ``["BlockRemoved", block_hashes, medium?]``
* ``AllBlocksCleared`` -> ``["AllBlocksCleared"]``

Block hashes arrive as integers (legacy) or byte strings (``sha256_cbor``
engines); they are normalized to uint64 downstream
(``token_processor.engine_hash_to_uint64``).  Decoders tolerate missing
optional trailing fields and ignore unknown extra fields, matching the
reference's legacy-format handling (process_event_test.go:38-60).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Union

import msgpack

BLOCK_STORED_TAG = "BlockStored"
BLOCK_REMOVED_TAG = "BlockRemoved"
ALL_BLOCKS_CLEARED_TAG = "AllBlocksCleared"


@dataclass
class BlockStored:
    block_hashes: List[Any]
    parent_block_hash: Optional[Any]
    token_ids: List[int]
    block_size: int
    lora_id: Optional[int] = None
    medium: Optional[str] = None
    lora_name: Optional[str] = None

    def to_tagged_union(self) -> List[Any]:
        return [
            BLOCK_STORED_TAG,
            self.block_hashes,
            self.parent_block_hash,
            self.token_ids,
            self.block_size,
            self.lora_id,
            self.medium,
            self.lora_name,
        ]


@dataclass
class BlockRemoved:
    block_hashes: List[Any]
    medium: Optional[str] = None

    def to_tagged_union(self) -> List[Any]:
        return [BLOCK_REMOVED_TAG, self.block_hashes, self.medium]


@dataclass
class AllBlocksCleared:
    def to_tagged_union(self) -> List[Any]:
        return [ALL_BLOCKS_CLEARED_TAG]


Event = Union[BlockStored, BlockRemoved, AllBlocksCleared]


@dataclass
class EventBatch:
    ts: float
    events: List[Any]  # raw (undecoded) tagged-union arrays
    data_parallel_rank: Optional[int] = None

    def encode(self) -> bytes:
        """Encode with each event as a tagged-union array."""
        encoded_events = [
            e.to_tagged_union() if hasattr(e, "to_tagged_union") else e
            for e in self.events
        ]
        body: List[Any] = [self.ts, encoded_events]
        if self.data_parallel_rank is not None:
            body.append(self.data_parallel_rank)
        return msgpack.packb(body, use_bin_type=True)


class EventDecodeError(ValueError):
    pass


def decode_event_batch(payload: bytes) -> EventBatch:
    try:
        raw = msgpack.unpackb(payload, raw=False, strict_map_key=False)
    except Exception as exc:  # malformed msgpack is a poison pill
        raise EventDecodeError(f"undecodable event batch: {exc}") from exc
    if not isinstance(raw, (list, tuple)) or len(raw) < 2:
        raise EventDecodeError(f"malformed event batch: {raw!r}")
    # Conversions guarded so type-confused payloads stay poison pills
    # (EventDecodeError) instead of escaping as TypeError/ValueError and
    # killing a pool worker.
    try:
        ts = float(raw[0])
    except (TypeError, ValueError) as exc:
        raise EventDecodeError(f"batch ts is not a number: {raw[0]!r}") from exc
    if not math.isfinite(ts):
        # ts is currently write-only in this codebase, but a nan/inf
        # timestamp is evidence the producer (or the wire) is corrupt —
        # the whole batch is treated as a poison pill rather than
        # trusting its events, and any future consumer of ts is
        # guaranteed a finite value.
        raise EventDecodeError(f"batch ts is not finite: {ts!r}")
    events = raw[1]
    if not isinstance(events, (list, tuple)):
        raise EventDecodeError("event batch events field is not an array")
    dp_rank = None
    if len(raw) >= 3 and raw[2] is not None:
        try:
            dp_rank = int(raw[2])
        except (TypeError, ValueError, OverflowError) as exc:
            raise EventDecodeError(
                f"batch dp rank is not an int: {raw[2]!r}"
            ) from exc
    return EventBatch(ts=ts, events=list(events), data_parallel_rank=dp_rank)


def _optional(fields: Sequence[Any], idx: int, default=None):
    if len(fields) > idx and fields[idx] is not None:
        return fields[idx]
    return default


def decode_event(raw: Any) -> Event:
    """Decode one tagged-union array into an event object."""
    if not isinstance(raw, (list, tuple)) or not raw:
        raise EventDecodeError(f"malformed tagged union: {raw!r}")
    tag = raw[0]
    if isinstance(tag, bytes):
        try:
            tag = tag.decode()
        except UnicodeDecodeError as exc:
            raise EventDecodeError(f"non-UTF-8 event tag: {tag!r}") from exc
    fields = raw[1:]

    try:
        if tag == BLOCK_STORED_TAG:
            if len(fields) < 4:
                raise EventDecodeError(
                    f"BlockStored requires 4 fields, got {len(fields)}"
                )
            medium = _optional(fields, 5)
            lora_name = _optional(fields, 6)
            return BlockStored(
                block_hashes=list(fields[0]),
                parent_block_hash=fields[1],
                token_ids=[int(t) for t in (fields[2] or [])],
                block_size=int(fields[3]),
                lora_id=_optional(fields, 4),
                medium=(
                    medium.decode() if isinstance(medium, bytes) else medium
                ),
                lora_name=(
                    lora_name.decode()
                    if isinstance(lora_name, bytes)
                    else lora_name
                ),
            )
        if tag == BLOCK_REMOVED_TAG:
            if len(fields) < 1:
                raise EventDecodeError("BlockRemoved requires a hash list")
            medium = _optional(fields, 1)
            return BlockRemoved(
                block_hashes=list(fields[0]),
                medium=(
                    medium.decode() if isinstance(medium, bytes) else medium
                ),
            )
    except (TypeError, ValueError, OverflowError, UnicodeDecodeError) as exc:
        # Field-level type confusion (an int where a list belongs, a
        # dict token id, non-UTF-8 medium bytes, int(inf) overflow, ...)
        # is a poison pill, not a worker-killing exception.
        if isinstance(exc, EventDecodeError):
            raise
        raise EventDecodeError(
            f"type-confused {tag} event: {exc}"
        ) from exc
    if tag == ALL_BLOCKS_CLEARED_TAG:
        return AllBlocksCleared()
    raise EventDecodeError(f"unknown event tag: {tag!r}")
