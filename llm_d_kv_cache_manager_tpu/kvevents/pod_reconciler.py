"""Kubernetes pod discovery driving the subscriber manager.

Counterpart of the reference's controller-runtime reconciler
(examples/kv_events/pod_reconciler/pod_reconciler.go:86-188): watch pods
matching a label selector; a Running+Ready pod with an IP gets a ZMQ
subscriber at ``tcp://<podIP>:<port>``, anything else (deleted, not
ready, IP-less) gets its subscriber removed.

The image ships no kubernetes client, so this speaks the watch API
directly over stdlib HTTP — in-cluster service-account auth, list to a
``resourceVersion``, then a chunked ``?watch=true`` stream of
ADDED/MODIFIED/DELETED JSON lines, re-listing on 410 Gone.  The same
predicates run either way, so tests drive it with a plain fake API
server.
"""

from __future__ import annotations

import json
import os
import socket
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Dict, Optional

from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
    SubscriberManager,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("kvevents.pod_reconciler")

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
# The fleet's serving pods carry this label (reference: pool.go:35).
DEFAULT_LABEL_SELECTOR = "llm-d.ai/inferenceServing=true"


@dataclass
class PodReconcilerConfig:
    namespace: Optional[str] = None  # None = service-account namespace
    label_selector: str = DEFAULT_LABEL_SELECTOR
    socket_port: int = 5557
    # Subscriber ids are k8s namespaced names, not the engines'
    # published pod ids — match every kv topic on each pod's socket.
    topic_filter: str = "kv@"
    # Overrides for out-of-cluster use / tests; in-cluster values are
    # discovered from the environment and service-account files.
    api_server: Optional[str] = None
    token: Optional[str] = None
    ca_cert_path: Optional[str] = None
    reconnect_seconds: float = 5.0
    # Server-side watch expiry: the API server ends the stream after this
    # many seconds and the loop re-lists — the liveness bound that keeps a
    # half-open TCP connection (node failover, LB idle drop without FIN)
    # from blocking the reconciler forever.  The socket read timeout is
    # set slightly above it so it only trips on genuinely dead streams.
    watch_timeout_seconds: float = 240.0


class KubeClient:
    """The two API calls the reconciler needs: list + watch pods."""

    def __init__(self, config: PodReconcilerConfig) -> None:
        self.config = config
        self.api_server = config.api_server or self._in_cluster_server()
        self.token = config.token or self._read_service_account("token")
        self.namespace = config.namespace or self._read_service_account(
            "namespace"
        )
        self._ssl_context = self._build_ssl_context()

    @staticmethod
    def _in_cluster_server() -> str:
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError(
                "not in-cluster (KUBERNETES_SERVICE_HOST unset) and no "
                "api_server configured"
            )
        return f"https://{host}:{port}"

    @staticmethod
    def _read_service_account(name: str) -> Optional[str]:
        path = os.path.join(SERVICE_ACCOUNT_DIR, name)
        if os.path.isfile(path):
            with open(path) as handle:
                return handle.read().strip()
        return None

    def _build_ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.api_server.startswith("https"):
            return None
        ca_path = self.config.ca_cert_path or os.path.join(
            SERVICE_ACCOUNT_DIR, "ca.crt"
        )
        if os.path.isfile(ca_path):
            return ssl.create_default_context(cafile=ca_path)
        return ssl.create_default_context()

    def _open(self, path: str, timeout: Optional[float]):
        request = urllib.request.Request(self.api_server + path)
        if self.token:
            request.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(
            request, timeout=timeout, context=self._ssl_context
        )

    def _pods_path(self, query: Dict[str, str]) -> str:
        namespace = self.namespace or "default"
        return (
            f"/api/v1/namespaces/{namespace}/pods?"
            + urllib.parse.urlencode(query)
        )

    def list_pods(self) -> dict:
        query = {"labelSelector": self.config.label_selector}
        with self._open(self._pods_path(query), timeout=30) as response:
            return json.load(response)

    def watch_pods(self, resource_version: str):
        """Yield watch events until the stream ends or errors."""
        watch_timeout = self.config.watch_timeout_seconds
        query = {
            "labelSelector": self.config.label_selector,
            "watch": "true",
            "resourceVersion": resource_version,
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(int(watch_timeout)),
        }
        # A healthy stream ends server-side at timeoutSeconds; the read
        # timeout sits above that so it fires only when the connection is
        # half-open and no FIN will ever arrive.
        with self._open(
            self._pods_path(query), timeout=watch_timeout + 60
        ) as response:
            for line in response:
                line = line.strip()
                if line:
                    yield json.loads(line)


class PodReconciler:
    """Keeps subscriber state converged with the live pod set."""

    def __init__(
        self,
        subscriber_manager: SubscriberManager,
        config: Optional[PodReconcilerConfig] = None,
        client: Optional[KubeClient] = None,
    ) -> None:
        self.config = config or PodReconcilerConfig()
        self.subscriber_manager = subscriber_manager
        self.client = client or KubeClient(self.config)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- predicates (pod_reconciler.go:135-158) --

    @staticmethod
    def _pod_key(pod: dict) -> str:
        # A list item can be a dict whose "metadata" is null/string/list;
        # the key computation runs outside _reconcile_safely (reconcile_list
        # marks pods "seen" regardless of reconcile outcome), so it must
        # never raise — one poison pod would wedge every resync.
        metadata = pod.get("metadata")
        if not isinstance(metadata, dict):
            metadata = {}
        return f"{metadata.get('namespace', '')}/{metadata.get('name', '')}"

    @staticmethod
    def should_subscribe(pod: dict) -> bool:
        """Running, has an IP, and Ready."""
        status = pod.get("status", {})
        if status.get("phase") != "Running":
            return False
        if not status.get("podIP"):
            return False
        return any(
            condition.get("type") == "Ready"
            and condition.get("status") == "True"
            for condition in status.get("conditions", [])
        )

    def _endpoint(self, pod: dict) -> str:
        ip = pod["status"]["podIP"].strip()
        if ":" in ip:  # IPv6
            ip = f"[{ip}]"
        return f"tcp://{ip}:{self.config.socket_port}"

    # -- reconciliation --

    def _reconcile_safely(
        self, event_type: str, pod: dict, context: str
    ) -> None:
        """The per-item poison-skip policy, shared by the list and watch
        paths: a pod that fails to reconcile is logged and skipped,
        never allowed to abort the cycle."""
        try:
            self.reconcile(event_type, pod)
        except Exception:  # noqa: BLE001 - per-item poison skip
            logger.warning(
                "skipping pod %s that failed to reconcile: %r",
                context,
                pod,
                exc_info=True,
            )

    def reconcile(self, event_type: str, pod: dict) -> None:
        key = self._pod_key(pod)
        if event_type == "DELETED":
            self.subscriber_manager.remove_subscriber(key)
            return
        if self.should_subscribe(pod):
            self.subscriber_manager.ensure_subscriber(
                key, self._endpoint(pod), topic_filter=self.config.topic_filter
            )
        else:
            self.subscriber_manager.remove_subscriber(key)

    def reconcile_list(self, pod_list: dict) -> str:
        """Full resync from a list response; returns its resourceVersion.

        Per-item poison skip like the watch path: one malformed pod in
        the list must not abort the resync — run_once re-lists FIRST
        every cycle, so an aborting item would wedge the reconciler for
        as long as it exists."""
        seen = set()
        if not isinstance(pod_list, dict):
            logger.warning("malformed pod list response %r", type(pod_list))
            pod_list = {}
        items = pod_list.get("items")
        if not isinstance(items, (list, tuple)):
            # Go serializes an empty slice as null; a proxy may mangle
            # worse.  A malformed items field must not raise — run_once
            # re-lists first every cycle, so raising here wedges the
            # reconciler (no watch ever starts) for as long as the
            # response shape persists.
            if items is not None:
                logger.warning("malformed pod list items %r", type(items))
            items = []
        for pod in items:
            if not isinstance(pod, dict):
                logger.warning("skipping malformed pod list item %r", pod)
                continue
            self._reconcile_safely("MODIFIED", pod, "list item")
            # Seen regardless of reconcile outcome: a pod PRESENT in the
            # list response must never be pruned below — a transient
            # ensure_subscriber failure would otherwise tear down that
            # pod's existing healthy subscription every resync.
            seen.add(self._pod_key(pod))
        for pod_id in self.subscriber_manager.active_pods():
            # "/" distinguishes reconciler-owned ids from manual ones
            # (e.g. the global-socket "local-subscriber").
            if "/" in pod_id and pod_id not in seen:
                self.subscriber_manager.remove_subscriber(pod_id)
        meta = pod_list.get("metadata")
        if not isinstance(meta, dict):
            meta = {}
        version = meta.get("resourceVersion", "0")
        return version if isinstance(version, str) else "0"

    # -- watch loop --

    def run_once(self) -> None:
        """One list+watch cycle (returns when the stream drops)."""
        resource_version = self.reconcile_list(self.client.list_pods())
        try:
            for event in self.client.watch_pods(resource_version):
                if self._stop.is_set():
                    return
                if not isinstance(event, dict):
                    # Valid JSON, wrong shape: skip the line rather than
                    # abort the watch (poison-pill philosophy of
                    # kvevents/pool.py; the stream itself is still
                    # framed correctly).
                    logger.warning("skipping malformed watch event %r", event)
                    continue
                kind = event.get("type", "")
                if kind == "BOOKMARK":
                    continue
                if kind == "ERROR":
                    # e.g. 410 Gone: resourceVersion too old -> re-list.
                    logger.info("watch error event %s; re-listing", event)
                    return
                obj = event.get("object", {})
                if not isinstance(obj, dict):
                    logger.warning("skipping malformed pod object %r", obj)
                    continue
                if obj.get("kind") not in (None, "Pod"):
                    continue
                self._reconcile_safely(kind, obj, "watch event")
        except (TimeoutError, socket.timeout):
            # Dead (half-open) stream: treat like a normal stream end and
            # let the loop re-list.  socket.timeout is only an alias of
            # TimeoutError from Python 3.10; catch both so older
            # interpreters get the quiet re-list too.
            logger.info("watch read timed out; re-listing")

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception as exc:
                logger.warning(
                    "pod watch failed (%s); retrying in %.0fs",
                    exc,
                    self.config.reconnect_seconds,
                )
            self._stop.wait(self.config.reconnect_seconds)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="kvtpu-pod-reconciler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
