"""Consolidated event-plane poller: many pods, a fixed thread pool.

The legacy subscription model spent one SUB socket **plus one dedicated
250 ms-poll thread per pod**, so a 10k-pod fleet meant 10k threads and
40k idle wakeups/s before a single event arrived — a hard ceiling far
below fleet scale.  This module replaces it with a small fixed pool of
poller threads (default 1, ``KVEVENTS_POLLERS``), each multiplexing
*many* SUB sockets through one ``zmq.Poller``:

* **threads** scale with ``KVEVENTS_POLLERS``, not fleet size;
* **idle wakeups** are one per poller per ``poll_interval_ms``,
  amortized over every attached pod;
* **reconnect/backoff** is poller-scheduled (a due-time per channel,
  folded into the poll timeout) instead of a per-thread sleep;
* **per-topic seq tracking** (gap / publisher-restart classification)
  moves into the shared demux (``zmq_subscriber.parse_event_message``),
  one ``TopicSeqTracker`` per channel, owned by the channel's poller
  thread.

``SubscriberManager`` is the public face: it became a registry that
attaches/detaches :class:`ChannelConfig`\\ s to this pool.  The bench's
``event_storm`` regime A/Bs this pool against the legacy
thread-per-pod baseline (``ZMQSubscriber``).

Thread-safety model: each channel (socket + tracker) is owned by
exactly one poller thread.  Cross-thread mutation happens only through
the command queue (attach/detach/shutdown) and the ``detached`` flag —
a plain boolean flip that makes delivery stop *immediately* (checked
before every sink call), while the socket itself is unregistered and
closed by the owning poller on its next wakeup.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import zmq

from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    resolve_lockfree_decode_env,
)
from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
    GapListener,
    TopicSeqTracker,
    open_sub_socket,
    parse_event_message,
    topic_filter_bytes,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("kvevents.poller")

# Messages drained per ready socket per wakeup: bounds how long one
# chatty pod can monopolize its poller before the next socket is
# served.  The shard queues do the real per-pod flow control; this is
# only poll-loop fairness.
MAX_RECV_PER_SOCKET = 64


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


@dataclass
class PollerPoolConfig:
    # Fixed poller-thread count.  One poller comfortably multiplexes
    # thousands of idle pods; raise it when decode-free receive work
    # itself saturates a core.  None -> KVEVENTS_POLLERS env (default 1).
    pollers: Optional[int] = None
    # Idle poll timeout.  Also the worst-case latency for picking up an
    # attach/detach command; commands additionally take effect
    # immediately via the `detached` flag.  None -> KVEVENTS_POLL_MS
    # env (default 50).
    poll_interval_ms: Optional[int] = None
    # Reconnect backoff after a socket error, scheduled on the poller's
    # clock (no per-pod sleeping thread).
    reconnect_backoff_s: float = 5.0
    # Zero-copy receive: payload frames are passed downstream as
    # memoryviews over the ZMQ message (no bytes copy per event).
    # None -> the same KVEVENTS_LOCKFREE_DECODE env the pool's
    # pre-decode stage reads — one knob flips the whole fast lane.
    zero_copy: Optional[bool] = None

    def resolved_pollers(self) -> int:
        n = self.pollers
        if n is None:
            n = _env_int("KVEVENTS_POLLERS", 1)
        return max(1, n)

    def resolved_poll_ms(self) -> int:
        ms = self.poll_interval_ms
        if ms is None:
            ms = _env_int("KVEVENTS_POLL_MS", 50)
        return max(1, ms)

    def resolved_zero_copy(self) -> bool:
        if self.zero_copy is not None:
            return self.zero_copy
        return resolve_lockfree_decode_env()


@dataclass
class ChannelConfig:
    """One pod's subscription: where to connect and what to filter."""

    endpoint: str
    pod_identifier: str
    topic_filter: Optional[str] = None
    bind: bool = False

    def filter_bytes(self) -> bytes:
        return topic_filter_bytes(self.topic_filter, self.pod_identifier)


class Channel:
    """A pod's socket + demux state, owned by one poller thread.

    Created by the manager, handed to a poller via ``attach``; after
    ``detach`` the manager must drop its reference (a new subscription
    for the same pod is a NEW channel — generation safety without
    generation counters).
    """

    __slots__ = (
        "config",
        "sink",
        "sink_batch",
        "on_gap",
        "tracker",
        "sock",
        "reconnect_at",
        "detached",
        "poller_index",
    )

    def __init__(
        self,
        config: ChannelConfig,
        sink: Callable[[Message], None],
        on_gap: Optional[GapListener] = None,
        sink_batch: Optional[Callable[[List[Message]], None]] = None,
    ) -> None:
        self.config = config
        self.sink = sink
        # Batched delivery (``Pool.add_tasks``): one sink call per
        # socket burst instead of one per message — one shard-lock
        # round trip and one metrics pass for the whole burst.  When
        # None, messages are delivered one by one through ``sink``.
        self.sink_batch = sink_batch
        self.on_gap = on_gap
        self.tracker = TopicSeqTracker()
        self.sock: Optional[zmq.Socket] = None
        self.reconnect_at = 0.0  # 0 = connect on first wakeup
        # Flipped by detach() from any thread; checked before every
        # sink delivery, so no events are delivered after detach even
        # while the socket awaits its poller-side close.
        self.detached = False
        self.poller_index = -1


class _Poller:
    """One poller thread multiplexing many channels via ``zmq.Poller``."""

    def __init__(
        self,
        index: int,
        context: zmq.Context,
        poll_interval_ms: int,
        reconnect_backoff_s: float,
        zero_copy: bool = True,
    ) -> None:
        self.index = index
        self._context = context
        self._poll_ms = poll_interval_ms
        self._backoff_s = reconnect_backoff_s
        self._zero_copy = zero_copy
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Pending attach(+)/detach(-) commands from other threads; the
        # only cross-thread channel mutation besides `detached`.  Leaf
        # lock: nothing is acquired while holding it.
        self._cmd_lock = lockorder.tracked(
            threading.Lock(), "Poller._cmd_lock"
        )
        self._commands: List[tuple] = []  # guarded-by: _cmd_lock
        # Channel count, maintained by the MANAGER side at
        # attach/detach time for least-loaded placement (the poller
        # thread's own dict lags by up to one wakeup).
        self._assigned = 0  # guarded-by: _cmd_lock

    # -- manager-side API ------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=self._run,
            name=f"kvtpu-evplane-poller-{self.index}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None

    def assigned(self) -> int:
        with self._cmd_lock:
            return self._assigned

    def alive(self) -> bool:
        """True while the poller thread is serving its channels."""
        thread = self._thread
        return thread is not None and thread.is_alive()

    def attach(self, channel: Channel) -> None:
        channel.poller_index = self.index
        with self._cmd_lock:
            self._commands.append(("attach", channel))
            self._assigned += 1

    def detach(self, channel: Channel) -> None:
        # Delivery stops NOW; the socket closes on the poller's next
        # wakeup (bounded by poll_interval_ms).
        channel.detached = True
        with self._cmd_lock:
            self._commands.append(("detach", channel))
            self._assigned -= 1

    # -- poller-thread internals ----------------------------------------

    def _open_socket(self, channel: Channel) -> zmq.Socket:
        return open_sub_socket(
            self._context,
            channel.config.endpoint,
            channel.config.filter_bytes(),
            channel.config.bind,
        )

    def _connect(
        self, channel: Channel, poller: zmq.Poller, now: float
    ) -> None:
        try:
            channel.sock = self._open_socket(channel)
            poller.register(channel.sock, zmq.POLLIN)
            channel.reconnect_at = 0.0
        except Exception as exc:  # noqa: BLE001 — endpoint may be bad
            channel.sock = None
            channel.reconnect_at = now + self._backoff_s
            logger.warning(
                "poller %d: connect to %s for pod %s failed (%s); "
                "retrying in %.0fs",
                self.index,
                channel.config.endpoint,
                channel.config.pod_identifier,
                exc,
                self._backoff_s,
            )

    def _teardown(
        self, channel: Channel, poller: zmq.Poller, now: float, exc: Exception
    ) -> None:
        """Socket error: close, schedule a poller-clock reconnect."""
        if channel.sock is not None:
            try:
                poller.unregister(channel.sock)
            except KeyError:
                pass
            channel.sock.close()
            channel.sock = None
        channel.reconnect_at = now + self._backoff_s
        logger.warning(
            "poller %d: socket for pod %s errored (%s); reconnecting "
            "in %.0fs",
            self.index,
            channel.config.pod_identifier,
            exc,
            self._backoff_s,
        )

    def _apply_commands(
        self,
        poller: zmq.Poller,
        channels: Dict[zmq.Socket, Channel],
        pending_connect: List[Channel],
    ) -> None:
        with self._cmd_lock:
            commands, self._commands = self._commands, []
        for op, channel in commands:
            if op == "attach":
                if channel.detached:  # attach/detach raced; never open
                    continue
                pending_connect.append(channel)
            else:  # detach
                if channel in pending_connect:
                    pending_connect.remove(channel)
                if channel.sock is not None:
                    try:
                        poller.unregister(channel.sock)
                    except KeyError:
                        pass
                    channels.pop(channel.sock, None)
                    channel.sock.close()
                    channel.sock = None

    def _run(self) -> None:
        poller = zmq.Poller()
        channels: Dict[zmq.Socket, Channel] = {}
        # Channels awaiting (re)connect, each with a due time on OUR
        # clock — the scheduled replacement for per-thread backoff
        # sleeps.
        pending_connect: List[Channel] = []
        sockets_gauge = METRICS.kvevents_poller_sockets.labels(
            poller=str(self.index)
        )
        last_socket_count = -1
        try:
            while not self._stop.is_set():
                self._apply_commands(poller, channels, pending_connect)
                now = time.monotonic()
                still_pending: List[Channel] = []
                for channel in pending_connect:
                    if channel.detached:
                        continue
                    if now >= channel.reconnect_at:
                        self._connect(channel, poller, now)
                        if channel.sock is not None:
                            channels[channel.sock] = channel
                            continue
                    still_pending.append(channel)
                pending_connect = still_pending
                if len(channels) != last_socket_count:
                    last_socket_count = len(channels)
                    sockets_gauge.set(last_socket_count)

                timeout_ms = self._poll_ms
                if pending_connect:
                    due = min(c.reconnect_at for c in pending_connect)
                    timeout_ms = min(
                        timeout_ms,
                        max(1, int((due - now) * 1000.0)),
                    )
                try:
                    ready = poller.poll(timeout_ms)
                except zmq.ZMQError:
                    if self._stop.is_set():
                        break
                    raise
                for sock, _flags in ready:
                    channel = channels.get(sock)
                    if channel is None:
                        continue
                    if channel.detached:
                        continue  # close happens via its command
                    try:
                        self._drain_socket(channel)
                    except zmq.ZMQError as exc:
                        channels.pop(sock, None)
                        self._teardown(
                            channel, poller, time.monotonic(), exc
                        )
                        pending_connect.append(channel)
        except Exception:  # noqa: BLE001 — a dead poller is fleet-wide loss
            logger.exception(
                "poller %d crashed; its pods stop receiving events "
                "until resubscribed",
                self.index,
            )
        finally:
            for sock in list(channels):
                sock.close()
            channels.clear()

    def _drain_socket(self, channel: Channel) -> None:
        """Receive up to MAX_RECV_PER_SOCKET messages without blocking,
        then deliver the burst in ONE batched sink call when the
        channel has one (``sink_batch`` -> ``Pool.add_tasks``: one
        shard-lock round trip for the whole burst, and the lock-free
        decode stage runs here on this poller thread).  Zero-copy mode
        hands the payload frame downstream as a memoryview — the tiny
        topic/seq frames are copied, the msgpack body is not."""
        assert channel.sock is not None
        batch: List[Message] = []
        for _ in range(MAX_RECV_PER_SOCKET):
            try:
                if self._zero_copy:
                    frames = channel.sock.recv_multipart(
                        zmq.NOBLOCK, copy=False
                    )
                    if len(frames) == 3:
                        parts = [
                            frames[0].bytes,
                            frames[1].bytes,
                            frames[2].buffer,
                        ]
                    else:
                        parts = [f.bytes for f in frames]
                else:
                    parts = channel.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                break
            if channel.detached:
                return
            message = parse_event_message(
                parts,
                endpoint=channel.config.endpoint,
                pod_identifier=channel.config.pod_identifier,
                tracker=channel.tracker,
                on_gap=channel.on_gap,
            )
            if message is None:
                continue
            batch.append(message)
        if not batch or channel.detached:
            return
        if channel.sink_batch is not None:
            try:
                channel.sink_batch(batch)
            except Exception:  # noqa: BLE001 — sink bugs must not kill us
                logger.exception(
                    "batch sink failed for %d messages from %s; dropping",
                    len(batch),
                    channel.config.pod_identifier,
                )
            return
        for message in batch:
            try:
                channel.sink(message)
            except Exception:  # noqa: BLE001 — sink bugs must not kill us
                logger.exception(
                    "sink failed for a message from %s; dropping it",
                    channel.config.pod_identifier,
                )


class PollerPool:
    """A fixed pool of :class:`_Poller` threads; channels attach to the
    least-loaded poller.  Threads start lazily on first attach so
    constructing a manager stays free."""

    def __init__(
        self,
        context: Optional[zmq.Context] = None,
        config: Optional[PollerPoolConfig] = None,
    ) -> None:
        self.config = config or PollerPoolConfig()
        self._context = context or zmq.Context.instance()
        # Lifecycle lock (leaf): guards lazy start + shutdown flag; a
        # wedged poller join never happens under it.
        self._lock = lockorder.tracked(threading.Lock(), "PollerPool._lock")
        self._pollers: List[_Poller] = []  # guarded-by: _lock
        self._started = False  # guarded-by: _lock
        self._shutdown = False  # guarded-by: _lock

    def _new_poller(self, index: int) -> _Poller:
        poller = _Poller(
            index,
            self._context,
            self.config.resolved_poll_ms(),
            self.config.reconnect_backoff_s,
            zero_copy=self.config.resolved_zero_copy(),
        )
        poller.start()
        return poller

    def _ensure_started(self) -> List[_Poller]:
        with self._lock:
            if self._shutdown:
                raise RuntimeError("PollerPool is shut down")
            if not self._started:
                self._started = True
                for i in range(self.config.resolved_pollers()):
                    self._pollers.append(self._new_poller(i))
            else:
                for i, poller in enumerate(self._pollers):
                    if not poller.alive():
                        # A crashed poller's channels are already lost
                        # (its pods must resubscribe) — but left in the
                        # pool it would keep collecting NEW attach
                        # assignments that can never deliver.  Replace
                        # it so fresh subscriptions land on a live
                        # thread.
                        logger.warning(
                            "poller %d found dead; replacing it "
                            "(its previous pods need resubscribing)",
                            poller.index,
                        )
                        self._pollers[i] = self._new_poller(
                            poller.index
                        )
            return list(self._pollers)

    def attach(
        self,
        config: ChannelConfig,
        sink: Callable[[Message], None],
        on_gap: Optional[GapListener] = None,
        sink_batch: Optional[Callable[[List[Message]], None]] = None,
    ) -> Channel:
        pollers = self._ensure_started()
        channel = Channel(config, sink, on_gap=on_gap, sink_batch=sink_batch)
        target = min(pollers, key=lambda p: p.assigned())
        target.attach(channel)
        return channel

    def detach(self, channel: Channel) -> None:
        with self._lock:
            pollers = list(self._pollers)
        for poller in pollers:
            if poller.index == channel.poller_index:
                poller.detach(channel)
                return
        # Pool already torn down: just stop delivery.
        channel.detached = True

    def poller_count(self) -> int:
        with self._lock:
            if not self._started:
                return self.config.resolved_pollers()
            return len(self._pollers)

    def shutdown(self) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            pollers, self._pollers = self._pollers, []
        # Join outside the lock: a wedged poller must not stall the
        # caller's other teardown work behind the pool lock.
        for poller in pollers:
            poller.stop()
