"""Sharded, per-pod-ordered event ingestion pool (the index write path).

Messages are sharded onto worker threads by ``FNV-1a-32(pod_id) % N`` so
events from one pod are always processed in publish order while the fleet
fans out across workers (reference: pkg/kvevents/pool.go:161-173).

Digest semantics (reference pool.go:233-334):

* ``BlockStored``: engine keys come from the event's hashes (normalized to
  uint64); request keys are *recomputed* from the event's token IDs with
  the indexer's own hash chain, chaining off the parent block's request key
  via ``index.get_request_key`` — the dual-key design that makes routing
  independent of per-engine hash configuration.  LoRA name, when present,
  replaces the model name in the hash chain.  Tier comes from ``medium``
  (lowercased), default "hbm" for TPU fleets.
* ``BlockRemoved``: evict each engine key.
* ``AllBlocksCleared``: intentionally a no-op, matching the reference
  (pool.go:328-329) — engines emit granular removals too.

Poison pills (undecodable payloads) are dropped, never retried.

An optional persistence journal (``persistence/journal.py``) taps the
post-apply path: every successful ``index.add``/``evict`` is appended as
an applied-operation record, which is what makes warm indexer restarts
possible (see docs/persistence.md).

**Per-pod flow control** (docs/event-plane.md): each shard queue keeps a
FIFO *lane per pod* instead of one global FIFO.  Workers drain lanes
round-robin (one message per pod per rotation), so a chatty pod shares
the batch with everyone else instead of monopolizing it.  Shedding is
budgeted per pod: a pod whose lane reaches ``PoolConfig.pod_budget``
sheds its OWN oldest message, and when the whole shard is full the
victim is the pod with the longest lane — which is always at or over
its fair share (``max_queue_depth // active pods``), so **a pod under
its effective budget** (``min(pod_budget, max_queue_depth // active
pods)``) **is never shed** — the fairness property the event_storm
bench and the property tests pin.  Within one pod, drop-oldest is
unchanged: the newest events describe the pod's current cache contents;
stale ones were about to be superseded anyway, and per-pod relative
ordering of the survivors is preserved.  Sheds are counted both in
``kvtpu_kvevents_dropped_total{reason}`` (``queue_full`` — whole-shard
overflow, ``pod_budget`` — over-budget pod, ``shutdown``) and per pod
in ``kvtpu_kvevents_pod_shed_total{pod=...}``; per-pod backlog rides
the ``kvtpu_kvevents_pod_backlog{pod=...}`` gauge.
``PoolConfig.per_pod_flow_control=False`` restores the legacy global
FIFO + drop-oldest (the bench A/B baseline).

**Write-path fast lane** (docs/event-plane.md): enqueue is batched
(``add_tasks``: one shard-lock round trip per drained socket burst,
metrics batched outside every lock) and the overflow victim — the
longest lane — is picked O(1) from depth buckets instead of an
O(lanes) ``max`` scan under the shard lock (the scan serialized
enqueueing pollers against draining workers at saturation; BENCH_r06's
pollers=4 < pollers=1 inversion).  With ``PoolConfig.lockfree_decode``
(``KVEVENTS_LOCKFREE_DECODE``, default on) payloads are msgpack-decoded
on the enqueueing thread BEFORE the shard queue — a lock-free stage
over (possibly zero-copy ``memoryview``) payloads — and workers apply
pre-decoded batches; off restores the straight in-worker decode, the
parity oracle the write-path tests pin.  ``KVEVENTS_DIGEST_MEMO``
bounds a per-worker LRU of digested request-key chains so repeated
stores of the same block chain skip re-hashing (pure function of
parent key + model + tokens, so no invalidation exists to get wrong).
``stage_stats()`` reports the cumulative decode/apply wall-time split
for the bench's bottleneck attribution.

**Resync commands**: the anti-entropy path (``kvevents/resync.py``)
repairs a pod whose event stream gapped by enqueueing a
:class:`ResyncJob` through :meth:`Pool.enqueue_resync`.  The job rides
the pod's normal shard lane — so it is ordered against that pod's live
events — and is applied by the worker as *purge, then re-apply the
inventory snapshot* through the same batched-apply surface live events
use.  Resync commands are never shed (shedding one would strand the
pod suspect forever); a shutdown drop reports failure to the waiter.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    TokenProcessor,
    engine_hash_to_uint64,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    EventBatch,
    EventDecodeError,
    decode_event,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import (
    METRICS,
    safe_label,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.obs.trace import (
    TRACER,
    Trace,
    current_trace,
    span as obs_span,
    use_trace,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger, trace

logger = get_logger("kvevents.pool")

# Pool lifecycle sits above the index in the lock hierarchy: a worker
# never holds the pool lock while applying into index shards, and the
# index never calls back into the pool.  Declared so both KV006 halves
# catch a future inversion (e.g. a drain that applies under _lock).
# kvlint: lock-order: Pool._lock < LRUCache._lock
lockorder.declare_order("Pool._lock", "LRUCache._lock")
# Shard-queue lanes are a leaf: put/get hold it only for deque surgery;
# metrics, trace bookkeeping, and index applies all happen outside.
# kvlint: lock-order: Pool._lock < ShardQueue._lock
lockorder.declare_order("Pool._lock", "ShardQueue._lock")

# TPU pods' on-chip tier; events without an explicit medium default here
# (GPU-era fleets default to "gpu" — both score 1.0 by default).
DEFAULT_EVENT_SOURCE_DEVICE_TIER = "hbm"


def resolve_lockfree_decode_env() -> bool:
    """The KVEVENTS_LOCKFREE_DECODE knob, shared by the pool's
    pre-decode stage and the poller's zero-copy receive so the two
    halves of the fast lane cannot drift apart.  Programmatic A/B runs
    that force ``PoolConfig(lockfree_decode=...)`` should set the
    poller's ``zero_copy`` to match."""
    return os.environ.get(
        "KVEVENTS_LOCKFREE_DECODE", "1"
    ).lower() not in ("0", "false", "no")

_FNV32_OFFSET = 0x811C9DC5
_FNV32_PRIME = 0x01000193


def fnv1a_32(data: bytes) -> int:
    h = _FNV32_OFFSET
    for byte in data:
        h = ((h ^ byte) * _FNV32_PRIME) & 0xFFFFFFFF
    return h


@dataclass
class ResyncJob:
    """An anti-entropy repair for one pod, applied in shard-lane order.

    ``events`` are decoded ``BlockStored`` inventory records in
    parent-chain order (``kvevents/resync.py`` builds them from an
    ``InventorySource`` snapshot).  The worker purges the pod's index
    entries, re-applies the inventory through the batched-apply
    surface, then calls ``on_done(job, ok, purged, detail)`` exactly
    once — also on shutdown-drop, so a waiter never hangs.
    """

    pod_identifier: str
    model_name: str
    events: List[object] = field(default_factory=list)
    # perf_counter timestamp when the pod was first marked suspect;
    # done-time minus this is the index-staleness window the bench and
    # the resync histogram report.
    suspect_since: float = 0.0
    on_done: Optional[Callable[["ResyncJob", bool, int, str], None]] = None
    purged: int = 0
    # First _finish wins: a job drained by a worker during shutdown and
    # then swept by the orphan pass must report exactly once.
    _done_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )
    _done: bool = field(default=False, repr=False)  # guarded-by: _done_lock

    def _finish(self, ok: bool, purged: int, detail: str) -> None:
        with self._done_lock:
            if self._done:
                return
            self._done = True
        self.purged = purged
        if self.on_done is not None:
            try:
                self.on_done(self, ok, purged, detail)
            except Exception:  # noqa: BLE001 — waiter bugs stay theirs
                logger.exception(
                    "resync on_done callback failed for pod %s",
                    self.pod_identifier,
                )


# Sentinel marking a message whose payload failed the lock-free
# pre-decode stage (poison pill discovered before the shard queue):
# the worker drops it without re-decoding.
_DECODE_FAILED = object()


@dataclass
class Message:
    """One raw event-stream message as received from a pod.

    ``payload`` may be ``bytes`` or a ``memoryview`` over the ZMQ frame
    (the poller's zero-copy path); it is only ever read by the decode
    stage, which accepts any bytes-like object.
    """

    topic: str
    payload: bytes
    pod_identifier: str
    model_name: str
    seq: int = 0
    # Events lost to a publisher sequence gap *immediately before* this
    # message (set by the subscriber); traced messages surface it so a
    # slow/strange apply can be correlated with upstream loss.
    seq_gap: int = 0
    # Sampled ingestion trace (obs/trace.py) riding the shard queue:
    # explicit propagation across the pool's thread boundary.
    trace: Optional[Trace] = None
    enqueued_at: float = 0.0
    # Anti-entropy command (see module docstring): when set the worker
    # purges + re-applies instead of decoding ``payload``; such command
    # messages are never shed by flow control.
    resync: Optional[ResyncJob] = None
    # Decoded EventBatch produced by the lock-free pre-decode stage
    # (``Pool.add_tasks`` with ``lockfree_decode`` on, running on the
    # enqueueing thread with no locks held): the worker skips its own
    # decode when set.  ``_DECODE_FAILED`` marks a poison pill already
    # counted/logged at pre-decode time.
    decoded: Optional[object] = None
    # Payload reference stashed by the input-capture tap BEFORE the
    # pre-decode stage clears ``payload`` (obs/capture.py): the
    # capture ring holds the message, and this field keeps the raw
    # bytes (possibly a zero-copy memoryview — pinned memory is
    # bounded by CAPTURE_MAX_BYTES) reachable for dump-time
    # serialization.  Never read by the pool itself.
    capture_payload: Optional[object] = None


@dataclass
class PoolConfig:
    concurrency: int = 4
    default_device_tier: str = DEFAULT_EVENT_SOURCE_DEVICE_TIER
    # Per-shard queue bound.  At the default, 4 shards hold up to 16k
    # in-flight messages (~tens of MB of msgpack) before load-shedding.
    max_queue_depth: int = 4096
    # Messages a worker drains per wake-up.  Under a backlog the whole
    # batch is decoded together and its index adds are grouped per
    # index shard before any lock is taken (``add_entries_batch``);
    # an idle stream degenerates to batch size 1 with no added
    # latency.  Observed in the kvtpu_kvevents_batch_size histogram.
    apply_batch_size: int = 32
    # Per-pod in-flight budget: a pod with this many queued messages in
    # its shard lane sheds its OWN oldest to admit a new one, whatever
    # the rest of the shard is doing.  None -> max_queue_depth (the
    # budget then only engages via whole-shard overflow, where the
    # longest lane is shed).  See module docstring for the fairness
    # property.
    pod_budget: Optional[int] = None
    # False restores the legacy single global FIFO per shard with
    # drop-oldest shedding (no lanes, no budget) — the event_storm
    # bench's A/B baseline and an escape hatch.
    per_pod_flow_control: bool = True
    # Lock-free decode stage: payloads are msgpack-decoded on the
    # enqueueing (poller) thread BEFORE the shard queue, with no locks
    # held, so workers spend their time applying.  None -> the
    # KVEVENTS_LOCKFREE_DECODE env (default on); False keeps the
    # straight in-worker decode path — the parity oracle the
    # write-path tests pin (docs/event-plane.md).
    lockfree_decode: Optional[bool] = None
    # Per-worker LRU of digested request-key chains keyed by
    # (parent request key, model, token ids): repeated stores of the
    # same block chain (shared prefixes fleet-wide, resync re-applies)
    # skip re-hashing entirely — block keys are pure functions of that
    # key, so the memo never needs invalidation (the PR-4 read-path
    # memo argument, applied to the write path).  None -> the
    # KVEVENTS_DIGEST_MEMO env (default 4096 entries); 0 disables.
    digest_memo: Optional[int] = None

    def effective_pod_budget(self) -> int:
        if self.pod_budget is None:
            return self.max_queue_depth
        return max(1, self.pod_budget)

    def resolved_lockfree_decode(self) -> bool:
        if self.lockfree_decode is not None:
            return self.lockfree_decode
        return resolve_lockfree_decode_env()

    def resolved_digest_memo(self) -> int:
        if self.digest_memo is not None:
            return max(0, self.digest_memo)
        try:
            return max(
                0, int(os.environ.get("KVEVENTS_DIGEST_MEMO", "4096"))
            )
        except ValueError:
            return 4096


class _ShardQueue:
    """Bounded per-shard message store with per-pod FIFO lanes.

    Replaces ``queue.Queue``: same blocking get / task accounting /
    close semantics, plus lane-aware shedding and round-robin drain
    (module docstring).  All methods are thread-safe; the lock is a
    leaf (deque surgery only — metrics and trace finishing happen in
    the caller, outside the lock).
    """

    def __init__(
        self, max_depth: int, pod_budget: int, per_pod: bool
    ) -> None:
        self._max_depth = max_depth
        self._pod_budget = pod_budget
        self._per_pod = per_pod
        # One Condition serves as both the mutex and the wake channel
        # (workers wait for work, join() waits for quiescence — the
        # while-loops disambiguate).  Tracked as the Condition itself,
        # the same shape as StagingBudget._cond: tracking the inner
        # lock would trip the watchdog on Condition's ownership probe.
        # kvlint: lock-order: Pool._lock < ShardQueue._lock
        self._lock = lockorder.tracked(
            threading.Condition(), "ShardQueue._lock"
        )
        # Lane order IS the drain rotation: the front lane serves one
        # message, then rotates to the back.
        self._lanes: "OrderedDict[str, Deque[Message]]" = (
            OrderedDict()
        )  # guarded-by: _lock
        self._regular: Dict[str, int] = {}  # guarded-by: _lock
        # Inverse index of ``_regular`` (depth -> ordered set of lane
        # keys at that depth) plus the current maximum, so the
        # overflow victim — the longest lane — is an O(1) pick.  The
        # old ``max(self._regular, key=...)`` was an O(lanes) scan
        # UNDER THE SHARD LOCK on every overflowing put: at saturation
        # with ~250 lanes/shard every enqueue paid it, pollers and
        # workers convoyed on the lock, and adding pollers made apply
        # throughput WORSE (the pollers=4 < pollers=1 inversion in
        # BENCH_r06).  Depths change by ±1 per operation, so bucket
        # moves (and the max's downward walk) are amortized O(1).
        self._by_depth: Dict[int, Dict[str, None]] = {}  # guarded-by: _lock
        self._max_lane = 0  # guarded-by: _lock
        self._size = 0  # guarded-by: _lock  (regular messages only)
        self._unfinished = 0  # guarded-by: _lock  (incl. commands)
        self._closed = False  # guarded-by: _lock

    def _lane_key(self, message: Message) -> str:
        return message.pod_identifier if self._per_pod else ""

    def _depth_move_locked(self, key: str, old: int, new: int) -> None:
        """Track one lane's regular-depth change in the depth buckets."""
        if old > 0:
            bucket = self._by_depth[old]
            del bucket[key]
            if not bucket:
                del self._by_depth[old]
        if new > 0:
            self._by_depth.setdefault(new, {})[key] = None
            if new > self._max_lane:
                self._max_lane = new
        while self._max_lane and self._max_lane not in self._by_depth:
            self._max_lane -= 1

    def _shed_from_locked(
        self, key: str, reason: str, shed: List[Tuple[Message, str]]
    ) -> None:
        """Pop the oldest REGULAR message from a lane (commands are
        never shed); caller holds the lock and guarantees one exists."""
        lane = self._lanes[key]
        stash: List[Message] = []
        victim: Optional[Message] = None
        while lane:
            candidate = lane.popleft()
            if candidate.resync is None:
                victim = candidate
                break
            stash.append(candidate)
        for command in reversed(stash):
            lane.appendleft(command)
        if victim is None:  # pragma: no cover — guarded by _regular
            return
        depth = self._regular[key]
        self._regular[key] = depth - 1
        self._depth_move_locked(key, depth, depth - 1)
        self._size -= 1
        self._unfinished -= 1
        if not lane:
            del self._lanes[key]
            del self._regular[key]
        shed.append((victim, reason))

    def _put_locked(
        self, message: Message, shed: List[Tuple[Message, str]]
    ) -> int:
        """Admit one message (caller holds the lock, queue not closed);
        returns the admitting lane's post-put regular depth."""
        key = self._lane_key(message)
        is_command = message.resync is not None
        lane = self._lanes.get(key)
        if not is_command:
            # Overflow outranks the budget label: at whole-shard
            # capacity the drop IS a queue_full event (the reason
            # dashboards have always alerted on), whoever the
            # victim — the longest lane, which is at or above its
            # effective budget by construction.  The pod_budget
            # reason is reserved for a pod hitting its own budget
            # while the shard still has room (otherwise legacy
            # single-lane mode, whose budget equals the depth,
            # would relabel every overflow drop).
            if self._size >= self._max_depth:
                victim_key = next(iter(self._by_depth[self._max_lane]))
                self._shed_from_locked(victim_key, "queue_full", shed)
            elif (
                lane is not None
                and self._regular.get(key, 0) >= self._pod_budget
            ):
                self._shed_from_locked(key, "pod_budget", shed)
            lane = self._lanes.get(key)
        if lane is None:
            lane = deque()
            self._lanes[key] = lane
            self._regular[key] = 0
        lane.append(message)
        if not is_command:
            depth = self._regular[key] + 1
            self._regular[key] = depth
            self._depth_move_locked(key, depth - 1, depth)
            self._size += 1
        self._unfinished += 1
        return self._regular[key]

    def put(self, message: Message) -> Tuple[List[Tuple[Message, str]], int]:
        """Admit a message, shedding per the flow-control policy.

        Returns ``(shed, lane_depth)``: messages displaced (with their
        shed reason) for the caller to count/finish outside the lock,
        and the admitting pod's lane depth after the put (-1 when the
        message itself was rejected at shutdown).
        """
        shed: List[Tuple[Message, str]] = []
        with self._lock:
            if self._closed:
                return [(message, "shutdown")], -1
            depth = self._put_locked(message, shed)
            self._lock.notify_all()
        return shed, depth

    def put_batch(
        self, messages: Sequence[Message]
    ) -> Tuple[List[Tuple[Message, str]], Dict[str, int]]:
        """Admit many messages under ONE lock round-trip (the batched
        poller sink).  Returns ``(shed, depths)``: displaced messages
        as in :meth:`put`, and each admitting pod's post-put lane depth
        (shutdown-rejected messages land in ``shed`` only)."""
        shed: List[Tuple[Message, str]] = []
        depths: Dict[str, int] = {}
        with self._lock:
            if self._closed:
                return [(m, "shutdown") for m in messages], {}
            for message in messages:
                depths[message.pod_identifier] = self._put_locked(
                    message, shed
                )
            self._lock.notify_all()
        return shed, depths

    def get_batch(
        self, limit: int
    ) -> Tuple[List[Message], bool, Dict[str, int]]:
        """Block for work; drain up to ``limit`` messages round-robin
        across lanes.  Returns ``(batch, closed, depths)`` where
        ``closed`` means the queue is closed AND fully drained, and
        ``depths`` is the post-drain regular backlog of every lane the
        batch touched (for the backlog gauge)."""
        with self._lock:
            while not self._lanes and not self._closed:
                self._lock.wait()
            if not self._lanes:
                return [], True, {}
            batch, depths = self._drain_locked(limit)
            return batch, False, depths

    def try_get_batch(
        self, limit: int
    ) -> Tuple[List[Message], Dict[str, int]]:
        """Non-blocking :meth:`get_batch`: returns ``([], {})``
        immediately when no lane holds work.  The deterministic inline
        drain path (``Pool.process_inline``) uses it — a blocking wait
        would deadlock a driver that IS the only producer."""
        with self._lock:
            if not self._lanes:
                return [], {}
            return self._drain_locked(limit)

    def _drain_locked(
        self, limit: int
    ) -> Tuple[List[Message], Dict[str, int]]:
        """Pop up to ``limit`` messages round-robin across lanes
        (caller holds the lock and guarantees at least one lane)."""
        batch: List[Message] = []
        depths: Dict[str, int] = {}
        while self._lanes and len(batch) < limit:
            key, lane = next(iter(self._lanes.items()))
            message = lane.popleft()
            batch.append(message)
            if message.resync is None:
                depth = self._regular[key]
                self._regular[key] = depth - 1
                self._depth_move_locked(key, depth, depth - 1)
                self._size -= 1
            depths[key] = self._regular.get(key, 0)
            if lane:
                self._lanes.move_to_end(key)
            else:
                del self._lanes[key]
                del self._regular[key]
        return batch, depths

    def task_done(self, count: int) -> None:
        if count <= 0:
            return
        with self._lock:
            self._unfinished -= count
            if self._unfinished <= 0:
                self._lock.notify_all()

    def join(self) -> None:
        with self._lock:
            while self._unfinished > 0:
                self._lock.wait()

    def close(self) -> List[Tuple[Message, str]]:
        """Mark closed and wake workers; queued messages still drain.
        Returns queued resync commands so the pool can fail their
        waiters if its workers are already gone."""
        with self._lock:
            if self._closed:
                return []
            self._closed = True
            self._lock.notify_all()
            return [
                message
                for lane in self._lanes.values()
                for message in lane
                if message.resync is not None
            ]

    def qsize(self) -> int:
        with self._lock:
            return self._size

    def lane_stats(self) -> Tuple[int, int]:
        """(queued regular messages, live lanes) — the timeline's
        shard-backlog/lane series (docs/observability.md)."""
        with self._lock:
            return self._size, len(self._lanes)

    def snapshot(self) -> List[Message]:
        """Queued messages in drain (round-robin) order — tests only."""
        with self._lock:
            lanes = [list(lane) for lane in self._lanes.values()]
        out: List[Message] = []
        index = 0
        while any(index < len(lane) for lane in lanes):
            for lane in lanes:
                if index < len(lane):
                    out.append(lane[index])
            index += 1
        return out

    def lane_depths(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._regular)


class _BatchApplier:
    """Groups index admissions across one drained message batch.

    Engine->request mappings publish EAGERLY (``add_mappings``): later
    events in the same batch resolve their parents through
    ``index.get_request_key``, so the map must always be current.  Pod
    entry admissions DEFER and flush grouped per index shard
    (``add_entries_batch``) — one lock round-trip per shard per batch
    instead of one per key.  Evictions act as barriers (the caller
    flushes before applying one) so an add->evict pair inside a batch
    never reorders into evict->add.  Journal records for deferred adds
    are written only after their flush succeeds, preserving the "a
    failed apply is never journaled" invariant; record order matches
    digest order (per-pod order is structural: one pod -> one shard
    queue).

    Backends without the batched surface (Redis, cost-aware) fall back
    to the per-event ``add`` path transparently.
    """

    __slots__ = (
        "_index",
        "_journal",
        "_batched",
        "_adds",
        "_records",
        "_traces",
        "_mappings",
    )

    def __init__(self, index: Index, journal) -> None:
        self._index = index
        self._journal = journal
        self._batched = callable(
            getattr(index, "add_entries_batch", None)
        ) and callable(getattr(index, "add_mappings", None))
        self._adds: List[tuple] = []  # (request_keys, entries)
        self._records: List[tuple] = []  # deferred journal record args
        # Traces owning the deferred adds.  A flush failure must error
        # exactly these — a mid-batch (eviction-barrier) flush can
        # discard admissions from EARLIER messages in the batch, whose
        # traces would otherwise finish "ok" at batch end.
        self._traces: List[Trace] = []
        # Engine->request mappings published by THIS batch: parent
        # resolution consults it before the index, so a parent stored
        # earlier in the batch resolves without a backend round trip —
        # for a remote backend (cluster/remote_index.py) that is one
        # RPC saved per chained event; for local backends it is merely
        # a dict hit instead of an LRU lock.  Mirrors already-published
        # state (add_mappings is eager), so semantics are unchanged.
        self._mappings: Dict[int, int] = {}

    def add(
        self,
        pod_identifier: str,
        seq: int,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
        owner_trace: Optional[Trace] = None,
    ) -> None:
        self._mappings.update(zip(engine_keys, request_keys))
        if not self._batched:
            self._index.add(engine_keys, request_keys, entries)
            if self._journal is not None:
                self._journal.record_add(
                    pod_identifier, seq, engine_keys, request_keys, entries
                )
            return
        self._index.add_mappings(engine_keys, request_keys)
        self._adds.append((request_keys, entries))
        if owner_trace is not None:
            self._traces.append(owner_trace)
        if self._journal is not None:
            self._records.append(
                (pod_identifier, seq, engine_keys, request_keys, entries)
            )

    def resolve_request_key(self, engine_key: int) -> int:
        """Parent resolution for chained events: the batch's own
        published mappings first, the index second.  Raises KeyError
        like ``Index.get_request_key``."""
        request_key = self._mappings.get(engine_key)
        if request_key is not None:
            return request_key
        return self._index.get_request_key(engine_key)

    def forget_mapping(self, engine_key: int) -> None:
        """Drop a batch-cached mapping after an eviction so parent
        resolution falls back to the index — the ground truth for
        whether the key survived.  Without this, a store chaining off
        an in-batch-evicted parent resolved or skipped depending on
        where the worker's batch boundary happened to fall (and the
        coalesced/uncoalesced streams could diverge the same way)."""
        self._mappings.pop(engine_key, None)

    def flush(self) -> None:
        """Apply deferred admissions (grouped per shard), then journal
        them.  Called before any eviction and at batch end."""
        if self._adds:
            adds, self._adds = self._adds, []
            traces, self._traces = self._traces, []
            try:
                self._index.add_entries_batch(adds)
            except Exception as exc:
                # The admissions never landed: their journal records
                # must die with them, or a later flush would journal
                # operations the live index never held ("a failed
                # apply is never journaled") — and their owning traces
                # must finish errored NOW, because the batch loop only
                # sees this exception through the triggering message
                # and would finish the earlier owners "ok".
                self._records = []
                for tr in traces:
                    tr.set_error(f"batched apply flush failed: {exc!r}")
                    tr.finish("error")
                raise
        if self._records:
            records, self._records = self._records, []
            for args in records:
                self._journal.record_add(*args)


class Pool:
    """N worker threads, each draining its own lane-structured queue.

    Each wake-up drains up to ``PoolConfig.apply_batch_size`` queued
    messages (round-robin across the shard's pod lanes), decodes them
    together, and applies them through a :class:`_BatchApplier` so
    admissions group per index shard before any lock is taken.
    Per-message traces, poison-pill handling, and per-pod ordering are
    unchanged from the one-message-at-a-time path; batch sizes land in
    ``kvtpu_kvevents_batch_size``.
    """

    def __init__(
        self,
        index: Index,
        token_processor: TokenProcessor,
        config: Optional[PoolConfig] = None,
        journal=None,
        capture=None,
    ) -> None:
        self.config = config or PoolConfig()
        if self.config.concurrency <= 0:
            raise ValueError("pool concurrency must be positive")
        self._index = index
        self._token_processor = token_processor
        # Optional persistence journal (persistence.Journal), tapped
        # AFTER each index apply succeeds: the journal records applied
        # operations, so replay needs no token re-hashing and a failed
        # apply is never journaled.  Per-pod order in the journal
        # matches apply order structurally (one pod -> one shard).
        self._journal = journal
        # Optional input flight recorder (obs/capture.py), tapped in
        # add_tasks POST shed decision: every ingress message lands in
        # the capture ring with its admitted/shed disposition so an
        # incident bundle can be replayed to a divergence
        # (obs/replay.py).  Resync commands are synthesized repairs,
        # not ingress, and are never recorded.  None (the default and
        # the CAPTURE=0 path) leaves the hot path with a single
        # ``is None`` check.
        self._capture = capture
        if self.config.max_queue_depth <= 0:
            raise ValueError("pool max_queue_depth must be positive")
        self._queues: List[_ShardQueue] = [
            _ShardQueue(
                self.config.max_queue_depth,
                self.config.effective_pod_budget(),
                self.config.per_pod_flow_control,
            )
            for _ in range(self.config.concurrency)
        ]
        self._threads: List[threading.Thread] = []  # guarded-by: _lock
        self._started = False  # guarded-by: _lock
        # Digest memo for the inline (single-threaded) drain path;
        # lazily built by process_inline, never shared with workers.
        self._inline_memo: Optional[OrderedDict] = None
        self._lock = lockorder.tracked(threading.Lock(), "Pool._lock")
        self._lockfree_decode = self.config.resolved_lockfree_decode()
        self._digest_memo_size = self.config.resolved_digest_memo()
        # Hot-path caches (racy-benign: values are deterministic, a
        # lost write is recomputed).  Bounded so a malformed-topic
        # flood cannot grow them without limit.
        self._shard_cache: Dict[str, int] = {}
        self._backlog_gauges: Dict[str, object] = {}
        self._shed_counters: Dict[str, object] = {}
        # Cumulative decode/apply wall-time split, wherever each stage
        # ran (pre-decode on the enqueueing thread or in-worker).  Fed
        # per batch, read by stage_stats() — the bench's
        # decode-vs-apply attribution.
        self._stage_lock = lockorder.tracked(
            threading.Lock(), "Pool._stage_lock"
        )
        self._stage = {  # guarded-by: _stage_lock
            "decode_s": 0.0,
            "decode_msgs": 0,
            "apply_s": 0.0,
            "apply_msgs": 0,
        }

    def set_capture(self, capture) -> None:
        """Attach/detach the input flight recorder (obs/capture.py)
        after construction — embedders that build the recorder late.
        Racy-benign: enqueueing threads read the attribute once per
        batch."""
        # gil-atomic: single ref store; enqueuers read one snapshot per batch
        self._capture = capture

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            for i in range(self.config.concurrency):
                thread = threading.Thread(
                    target=self._worker,
                    args=(i,),
                    name=f"kvtpu-events-{i}",
                    daemon=True,
                )
                thread.start()
                self._threads.append(thread)

    def shutdown(self) -> None:
        with self._lock:
            if not self._started:
                return
            orphaned: List[Message] = []
            for q in self._queues:
                orphaned.extend(q.close())
            threads = list(self._threads)
            self._threads.clear()
            self._started = False
        for thread in threads:
            thread.join(timeout=10)
        # Workers that exited without draining (or never existed) must
        # not leave resync waiters hanging.
        for message in orphaned:
            if message.resync is not None:
                message.resync._finish(False, 0, "pool shutdown")

    def drain(self) -> None:
        """Block until every queued message has been processed (tests)."""
        for q in self._queues:
            q.join()

    def process_inline(self, limit: int = 0) -> int:
        """Synchronously decode + apply queued messages on the CALLING
        thread — the deterministic drain primitive the what-if engine's
        virtual clock schedules against (obs/whatif.py).

        The pool must never have been ``start()``ed: with no workers,
        ``add_tasks`` flow-control decisions are pure data-structure
        ops and this call owns the only drain, so a given enqueue/drain
        schedule processes messages in exactly one order.  Drains up to
        ``limit`` messages (0 = everything currently queued), one
        apply-batch per shard per rotation (shard order, then each
        shard's own round-robin lanes).  Returns messages processed.
        """
        with self._lock:
            if self._started:
                raise RuntimeError(
                    "process_inline requires an un-started pool "
                    "(workers would race the inline drain)"
                )
        batch_limit = max(1, self.config.apply_batch_size)
        memo = self._inline_memo
        if memo is None and self._digest_memo_size:
            memo = self._inline_memo = OrderedDict()
        processed = 0
        while True:
            progressed = False
            for q in self._queues:
                take = batch_limit
                if limit > 0:
                    take = min(take, limit - processed)
                    if take <= 0:
                        return processed
                batch, depths = q.try_get_batch(take)
                if not batch:
                    continue
                for pod, depth in depths.items():
                    if pod:
                        self._backlog_gauge(pod).set(depth)
                try:
                    self._process_batch(batch, 0, memo)
                except Exception:  # noqa: BLE001 — mirror the worker
                    logger.exception(
                        "inline drain failed processing a batch; "
                        "dropping"
                    )
                finally:
                    q.task_done(len(batch))
                processed += len(batch)
                progressed = True
            if not progressed:
                return processed

    @staticmethod
    def _finish_dropped(dropped: Message, reason: str) -> None:
        """A shed message's trace must still reach the recorder: drops
        ARE the incident the flight recorder exists to explain."""
        if dropped.trace is not None:
            dropped.trace.set_error(f"dropped: {reason}")
            dropped.trace.finish("error")
        if dropped.resync is not None:
            dropped.resync._finish(False, 0, f"dropped: {reason}")

    def _shard_index(self, pod_identifier: str) -> int:
        shard = self._shard_cache.get(pod_identifier)
        if shard is None:
            shard = fnv1a_32(pod_identifier.encode()) % len(self._queues)
            if len(self._shard_cache) < 131072:
                # gil-atomic: idempotent memo; value is a pure function of the key
                self._shard_cache[pod_identifier] = shard
        return shard

    def _shard_for(self, pod_identifier: str) -> _ShardQueue:
        return self._queues[self._shard_index(pod_identifier)]

    def _backlog_gauge(self, pod_identifier: str):
        gauge = self._backlog_gauges.get(pod_identifier)
        if gauge is None:
            gauge = METRICS.kvevents_pod_backlog.labels(
                pod=safe_label(pod_identifier)
            )
            if len(self._backlog_gauges) < 131072:
                # gil-atomic: idempotent memo; racing put re-derives the same value
                self._backlog_gauges[pod_identifier] = gauge
        return gauge

    def _shed_counter(self, pod_identifier: str):
        counter = self._shed_counters.get(pod_identifier)
        if counter is None:
            counter = METRICS.kvevents_pod_shed.labels(
                pod=safe_label(pod_identifier)
            )
            if len(self._shed_counters) < 131072:
                # gil-atomic: idempotent memo; racing put re-derives the same value
                self._shed_counters[pod_identifier] = counter
        return counter

    def _stage_account(self, stage: str, seconds: float, msgs: int) -> None:
        with self._stage_lock:
            self._stage[f"{stage}_s"] += seconds
            self._stage[f"{stage}_msgs"] += msgs

    def stage_stats(self) -> dict:
        """Cumulative decode vs apply wall-time split (seconds and
        message counts), wherever each stage ran — the bench's
        bottleneck attribution (docs/event-plane.md)."""
        with self._stage_lock:
            return dict(self._stage)

    def lane_stats(self) -> Tuple[int, int]:
        """(queued-not-applied messages, pods holding a live lane)
        across every shard in ONE walk — the timeline samples both
        series every second off a single call, so the shard locks
        are taken once, not once per series (shards are sampled one
        lock at a time: a near-instant, not atomic, view)."""
        queued = 0
        lanes = 0
        for q in self._queues:
            shard_queued, shard_lanes = q.lane_stats()
            queued += shard_queued
            lanes += shard_lanes
        return queued, lanes

    def backlog(self) -> int:
        """Queued-not-applied messages across every shard."""
        return self.lane_stats()[0]

    def lane_count(self) -> int:
        """Pods holding a live (non-empty) lane across every shard."""
        return self.lane_stats()[1]

    def _prepare_message(self, message: Message) -> None:
        if message.trace is None:
            tr = TRACER.start_trace("kvevents.message")
            if tr is not None:
                tr.set_attr("pod", message.pod_identifier)
                tr.set_attr("topic", message.topic)
                tr.set_attr("seq", message.seq)
                message.trace = tr
        if message.trace is not None:
            message.enqueued_at = time.perf_counter()

    def _predecode(self, message: Message) -> None:
        """Lock-free decode stage: runs on the ENQUEUEING thread with
        no locks held, so workers never parse msgpack and enqueueing
        threads never hold a lock while parsing."""
        try:
            message.decoded = decode_event_batch(message.payload)
            # The payload is never read again once decoded; dropping it
            # now releases the zero-copy ZMQ frame instead of pinning
            # raw msgpack alongside the decoded batch for the whole
            # queue backlog lifetime.
            message.payload = b""
        except EventDecodeError as exc:
            message.decoded = _DECODE_FAILED
            logger.warning(
                "dropping poison-pill message from pod %s (topic %s): %s",
                message.pod_identifier,
                message.topic,
                exc,
            )
            if message.trace is not None:
                message.trace.set_error(f"poison pill: {exc}")
        except Exception as exc:  # noqa: BLE001 — decoder bug, not fatal
            message.decoded = _DECODE_FAILED
            logger.exception(
                "pre-decode failed for a message from pod %s; dropping",
                message.pod_identifier,
            )
            if message.trace is not None:
                message.trace.set_error(f"pre-decode crashed: {exc!r}")

    def add_task(self, message: Message) -> None:
        self.add_tasks((message,))

    def add_tasks(self, messages: Sequence[Message]) -> None:
        """Batched enqueue — the consolidated poller's sink.

        One shard-lock round trip per touched shard per call (vs one
        per message), metrics and trace bookkeeping batched outside
        every lock.  The lock-free decode stage runs here when enabled
        (``PoolConfig.lockfree_decode``): payloads are parsed on this
        thread with no locks held, and workers apply pre-decoded
        batches.
        """
        if not messages:
            return
        per_shard: Dict[int, List[Message]] = {}
        # Input capture copies payload bytes BEFORE the lock-free
        # pre-decode stage releases them (zero-copy ZMQ frames must
        # not be pinned by the ring, and pre-decode clears payload).
        cap = self._capture
        captured: Optional[List[Message]] = (
            [] if cap is not None else None
        )
        # Trace start BEFORE pre-decode: a poison pill found at decode
        # must still error its sampled trace for the flight recorder.
        for message in messages:
            self._prepare_message(message)
            if captured is not None and message.resync is None:
                message.capture_payload = message.payload
                captured.append(message)
            per_shard.setdefault(
                self._shard_index(message.pod_identifier), []
            ).append(message)
        if self._lockfree_decode:
            t0 = time.perf_counter()
            n_decoded = 0
            for message in messages:
                if message.resync is None and message.decoded is None:
                    self._predecode(message)
                    n_decoded += 1
            if n_decoded:
                self._stage_account(
                    "decode", time.perf_counter() - t0, n_decoded
                )
        shed_map: Dict[int, Tuple[Message, str]] = {}
        for shard, batch in per_shard.items():
            shed, depths = self._queues[shard].put_batch(batch)
            # Metrics + trace finishing OUTSIDE the shard lock.
            for dropped, reason in shed:
                if captured is not None:
                    shed_map[id(dropped)] = (dropped, reason)
                METRICS.kvevents_dropped.labels(reason=reason).inc()
                self._shed_counter(dropped.pod_identifier).inc()
                self._finish_dropped(dropped, reason)
                logger.debug(
                    "event shard shed a message from pod %s (%s)",
                    dropped.pod_identifier,
                    reason,
                )
            for pod, depth in depths.items():
                self._backlog_gauge(pod).set(depth)
        if captured is not None:
            try:
                self._capture_batch(cap, captured, shed_map)
            except Exception:  # noqa: BLE001 — capture never sheds work
                logger.exception("input capture failed for a batch")

    @staticmethod
    def _capture_batch(
        cap,
        captured: List[Message],
        shed_map: Dict[int, Tuple[Message, str]],
    ) -> None:
        """Record this enqueue burst post shed decision: every message
        of the burst lands once (admitted, or its shed reason); a
        message from an EARLIER burst displaced by this one gets a
        payload-free displacement record — replay cancels its admitted
        record against it (obs/replay.py).  The whole burst rides ONE
        recorder lock round trip so the tap stays inside the
        event_storm capture_ab overhead bound; the common no-shed
        burst takes the allocation-free admitted fast path (the ring
        holds the Message itself, expanded at dump time)."""
        if not shed_map:
            cap.record_admitted_messages(captured)
            return
        items = []
        for message in captured:
            entry = shed_map.pop(id(message), None)
            items.append(
                (
                    message.pod_identifier,
                    message.topic,
                    message.model_name,
                    message.seq,
                    message.seq_gap,
                    bytes(message.capture_payload),
                    "admitted" if entry is None else entry[1],
                )
            )
        for dropped, reason in shed_map.values():
            if dropped.resync is not None:
                continue
            items.append(
                (
                    dropped.pod_identifier,
                    dropped.topic,
                    dropped.model_name,
                    dropped.seq,
                    dropped.seq_gap,
                    None,
                    reason,
                )
            )
        cap.record_kvevents_batch(items)

    def enqueue_resync(self, job: ResyncJob, trace_: Optional[Trace] = None):
        """Queue an anti-entropy repair in the pod's shard lane (so it
        is ordered against the pod's live events)."""
        message = Message(
            topic=f"resync@{job.pod_identifier}",
            payload=b"",
            pod_identifier=job.pod_identifier,
            model_name=job.model_name,
            trace=trace_,
            resync=job,
        )
        if message.trace is not None:
            message.enqueued_at = time.perf_counter()
        shed, _depth = self._shard_for(job.pod_identifier).put(message)
        for dropped, reason in shed:
            # Only "shutdown" can reject a command message.
            METRICS.kvevents_dropped.labels(reason=reason).inc()
            self._finish_dropped(dropped, reason)

    def _worker(self, worker_index: int) -> None:
        q = self._queues[worker_index]
        batch_limit = max(1, self.config.apply_batch_size)
        # Per-worker digest memo: no cross-thread sharing, no lock —
        # a worker owns its pods (pod -> shard affinity), so its memo
        # naturally concentrates on the chains those pods re-store.
        memo: Optional[OrderedDict] = (
            OrderedDict() if self._digest_memo_size else None
        )
        while True:
            batch, closed, depths = q.get_batch(batch_limit)
            if closed:
                return
            for pod, depth in depths.items():
                if pod:
                    self._backlog_gauge(pod).set(depth)
            try:
                self._process_batch(batch, worker_index, memo)
            except Exception:
                # The batch loop guards decode and apply per message,
                # but the worker must survive ANYTHING escaping
                # (metrics observe, trace bookkeeping): a dead worker
                # means its shard's queue fills and every later event
                # for those pods is silently shed for the process
                # lifetime.
                logger.exception(
                    "event worker %d failed processing a batch; dropping",
                    worker_index,
                )
            finally:
                # task_done only after the batch (including the
                # deferred-add flush) has fully applied: drain() must
                # imply visibility.
                q.task_done(len(batch))

    def _process_batch(
        self,
        batch: List[Message],
        worker_index: int,
        memo: Optional[OrderedDict] = None,
    ) -> None:
        METRICS.kvevents_batch_size.observe(len(batch))
        applier = _BatchApplier(self._index, self._journal)
        decoded: List[Optional[EventBatch]] = []
        decode_t = 0.0
        decode_n = 0
        for message in batch:
            tr = message.trace
            if tr is not None:
                # Queue wait vs apply time is the shard-health split: a
                # storm shows up as queue_wait, a stuck index backend
                # as apply.
                tr.add_completed("kvevents.queue_wait", message.enqueued_at)
                if message.seq_gap:
                    tr.set_attr("seq_gap", message.seq_gap)
            if message.resync is not None:
                decoded.append(None)
                continue
            if message.decoded is not None:
                # Pre-decoded by the lock-free stage (poison pills were
                # already counted and their traces errored there).
                decoded.append(
                    None
                    if message.decoded is _DECODE_FAILED
                    else message.decoded
                )
                continue
            try:
                t0 = time.perf_counter()
                with use_trace(tr):
                    decoded.append(self._decode_message(message))
                decode_t += time.perf_counter() - t0
                decode_n += 1
            except Exception:
                logger.exception(
                    "event worker %d failed decoding a message; dropping",
                    worker_index,
                )
                decoded.append(None)
                if tr is not None:
                    tr.finish("error")
        if decode_n:
            self._stage_account("decode", decode_t, decode_n)
        # Traces of successfully-digested messages stay open until the
        # final flush lands: their adds may still be deferred in the
        # applier, and a trace that reported "ok" before its admissions
        # were applied would hide a flush failure from the flight
        # recorder.
        pending_traces: List[Trace] = []
        apply_t0 = time.perf_counter()
        apply_n = 0
        for message, events in zip(batch, decoded):
            tr = message.trace
            if message.resync is not None:
                # Barrier like evictions: the purge must not reorder
                # ahead of admissions digested earlier in this batch.
                applier.flush()
                self._apply_resync(message, worker_index, memo)
                continue
            if events is None:
                if tr is not None:
                    # Poison pill (error already set) or decode crash
                    # (already finished — finish() is idempotent).
                    tr.finish()
                continue
            try:
                with use_trace(tr):
                    self._apply_events(message, events, applier, memo)
                apply_n += 1
            except Exception as exc:
                if tr is not None:
                    tr.set_error(repr(exc))
                    tr.finish("error")
                logger.exception(
                    "event worker %d failed processing a message; dropping",
                    worker_index,
                )
                continue
            if tr is not None:
                pending_traces.append(tr)
        try:
            applier.flush()
        except Exception:
            logger.exception(
                "event worker %d failed flushing batched index adds; "
                "dropping the batch's deferred admissions",
                worker_index,
            )
        if apply_n:
            self._stage_account(
                "apply", time.perf_counter() - apply_t0, apply_n
            )
        # Applied messages may be retained by the input-capture ring
        # (compact records hold the Message itself); dropping the
        # decoded-batch and trace refs here keeps that retention at
        # payload cost, not payload + decoded-object + finished-trace
        # cost (the flight recorder holds its own trace refs, and
        # pending_traces below carries the ones still to finish).
        # The poison sentinel is a process-wide singleton — keep it
        # (it is the observable that pre-decode already classified
        # the message).
        for message in batch:
            if message.decoded is not _DECODE_FAILED:
                message.decoded = None
            message.trace = None
        # The applier already finished the traces owning any discarded
        # adds as errored (whether the failing flush was this final one
        # or a mid-batch eviction barrier); for everyone else the work
        # landed, so "ok" — finish() is idempotent, first call wins.
        for tr in pending_traces:
            tr.finish()

    def _apply_resync(
        self,
        message: Message,
        worker_index: int,
        memo: Optional[OrderedDict] = None,
    ) -> None:
        """Purge + re-apply one pod's inventory snapshot, atomically
        with respect to this worker (the pod's only event applier)."""
        job = message.resync
        assert job is not None
        tr = message.trace
        try:
            with use_trace(tr):
                with obs_span("kvevents.resync.apply") as s:
                    purged = self._index.purge_pod(job.pod_identifier)
                    if self._journal is not None:
                        # The purge must replay before the re-applied
                        # inventory (recovery + replication followers
                        # replay in journal order), or a crash between
                        # here and the next snapshot resurrects the
                        # purged claims.
                        self._journal.record_purge(job.pod_identifier)
                    applier = _BatchApplier(self._index, self._journal)
                    applied = 0
                    for event in job.events:
                        self._digest(message, event, applier, memo)
                        applied += 1
                    applier.flush()
                    s.set_attr("purged", purged)
                    s.set_attr("inventory_events", applied)
        except Exception as exc:
            logger.exception(
                "event worker %d failed resyncing pod %s",
                worker_index,
                job.pod_identifier,
            )
            if tr is not None:
                tr.set_error(f"resync apply failed: {exc!r}")
                tr.finish("error")
            job._finish(False, 0, f"apply failed: {exc!r}")
            return
        if tr is not None:
            tr.finish()
        job._finish(True, purged, "ok")

    def _decode_message(self, message: Message) -> Optional[EventBatch]:
        with obs_span("kvevents.decode") as s:
            try:
                batch = decode_event_batch(message.payload)
            except EventDecodeError as exc:
                # Data loss, not noise: this pod's cache state is now
                # stale until its next re-store event.
                logger.warning(
                    "dropping poison-pill message from pod %s (topic %s): %s",
                    message.pod_identifier,
                    message.topic,
                    exc,
                )
                active = current_trace()
                if active is not None:
                    active.set_error(f"poison pill: {exc}")
                return None
            s.set_attr("events", len(batch.events))
        return batch

    def _apply_events(
        self,
        message: Message,
        batch: EventBatch,
        applier: _BatchApplier,
        memo: Optional[OrderedDict] = None,
    ) -> None:
        with obs_span("kvevents.apply") as s:
            applied = 0
            for raw_event in batch.events:
                try:
                    event = decode_event(raw_event)
                except (EventDecodeError, TypeError, ValueError) as exc:
                    # Per-event skip: one malformed event must not drop
                    # the rest of the batch.
                    logger.debug("skipping undecodable event: %s", exc)
                    continue
                self._digest(message, event, applier, memo)
                applied += 1
            s.set_attr("applied", applied)

    def _digest(
        self,
        message: Message,
        event,
        applier: _BatchApplier,
        memo: Optional[OrderedDict] = None,
    ) -> None:
        if isinstance(event, BlockStored):
            self._digest_block_stored(message, event, applier, memo)
        elif isinstance(event, BlockRemoved):
            self._digest_block_removed(message, event, applier)
        elif isinstance(event, AllBlocksCleared):
            # Intentional no-op; granular BlockRemoved events follow.
            return

    def _tier(self, medium: Optional[str]) -> str:
        if medium:
            return medium.lower()
        return self.config.default_device_tier

    def _digest_block_stored(
        self,
        message: Message,
        event: BlockStored,
        applier: _BatchApplier,
        memo: Optional[OrderedDict] = None,
    ) -> None:
        entries = [PodEntry(message.pod_identifier, self._tier(event.medium))]

        # LoRA adapters have their own KV-incompatible hash space.
        effective_model = event.lora_name or message.model_name

        engine_keys = []
        for raw_hash in event.block_hashes:
            try:
                engine_keys.append(engine_hash_to_uint64(raw_hash))
            except (TypeError, ValueError) as exc:
                logger.debug("skipping bad block hash %r: %s", raw_hash, exc)
        if not engine_keys:
            return

        parent_request_key = EMPTY_BLOCK_HASH
        if event.parent_block_hash is not None:
            try:
                parent_engine_key = engine_hash_to_uint64(
                    event.parent_block_hash
                )
                parent_request_key = applier.resolve_request_key(
                    parent_engine_key
                )
            except (TypeError, ValueError, KeyError) as exc:
                # Parent unknown (evicted or never seen): skip the event
                # rather than index keys hashed off the wrong root.
                trace(
                    logger,
                    "parent block unresolvable for pod %s: %s",
                    message.pod_identifier,
                    exc,
                )
                return

        # Digest memo: request keys are a pure function of
        # (parent request key, model, token ids) — the token-processor
        # identity is fixed per pool — so a repeated chain skips the
        # hash work entirely.  Values are treated read-only everywhere
        # downstream (the overlap trim below slices a copy).
        memo_key = None
        request_keys = None
        if memo is not None:
            memo_key = (
                parent_request_key,
                effective_model,
                tuple(event.token_ids),
            )
            request_keys = memo.get(memo_key)
            if request_keys is not None:
                memo.move_to_end(memo_key)
        if request_keys is None:
            request_keys = self._token_processor.tokens_to_kv_block_keys(
                parent_request_key, event.token_ids, effective_model
            )
            if memo is not None:
                memo[memo_key] = request_keys
                if len(memo) > self._digest_memo_size:
                    memo.popitem(last=False)
        if len(request_keys) != len(engine_keys):
            logger.debug(
                "engine reported %d hashes but token ids produced %d request "
                "keys (pod %s); indexing the overlapping prefix",
                len(engine_keys),
                len(request_keys),
                message.pod_identifier,
            )
            overlap = min(len(request_keys), len(engine_keys))
            if overlap == 0:
                return
            engine_keys = engine_keys[:overlap]
            request_keys = request_keys[:overlap]

        applier.add(
            message.pod_identifier,
            message.seq,
            engine_keys,
            request_keys,
            entries,
            owner_trace=message.trace,
        )

    def _digest_block_removed(
        self, message: Message, event: BlockRemoved, applier: _BatchApplier
    ) -> None:
        # Eviction barrier: deferred adds must land first so an
        # add->evict pair inside one batch keeps its order.
        applier.flush()
        entries = [PodEntry(message.pod_identifier, self._tier(event.medium))]
        evicted_keys = []
        for raw_hash in event.block_hashes:
            try:
                engine_key = engine_hash_to_uint64(raw_hash)
            except (TypeError, ValueError) as exc:
                logger.debug("skipping bad removal hash %r: %s", raw_hash, exc)
                continue
            self._index.evict(engine_key, entries)
            applier.forget_mapping(engine_key)
            evicted_keys.append(engine_key)
        if self._journal is not None and evicted_keys:
            self._journal.record_evict(
                message.pod_identifier, message.seq, evicted_keys, entries
            )
