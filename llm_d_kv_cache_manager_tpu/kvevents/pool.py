"""Sharded, per-pod-ordered event ingestion pool (the index write path).

Messages are sharded onto worker threads by ``FNV-1a-32(pod_id) % N`` so
events from one pod are always processed in publish order while the fleet
fans out across workers (reference: pkg/kvevents/pool.go:161-173).

Digest semantics (reference pool.go:233-334):

* ``BlockStored``: engine keys come from the event's hashes (normalized to
  uint64); request keys are *recomputed* from the event's token IDs with
  the indexer's own hash chain, chaining off the parent block's request key
  via ``index.get_request_key`` — the dual-key design that makes routing
  independent of per-engine hash configuration.  LoRA name, when present,
  replaces the model name in the hash chain.  Tier comes from ``medium``
  (lowercased), default "hbm" for TPU fleets.
* ``BlockRemoved``: evict each engine key.
* ``AllBlocksCleared``: intentionally a no-op, matching the reference
  (pool.go:328-329) — engines emit granular removals too.

Poison pills (undecodable payloads) are dropped, never retried.

An optional persistence journal (``persistence/journal.py``) taps the
post-apply path: every successful ``index.add``/``evict`` is appended as
an applied-operation record, which is what makes warm indexer restarts
possible (see docs/persistence.md).

Each shard queue is *bounded* (``PoolConfig.max_queue_depth``, matching the
reference's bounded per-shard workqueues, pool.go:134-173).  When a shard
fills — an event storm, or a stuck index backend wedging one worker — the
pool drops the *oldest* queued message from that shard to admit the new
one, and counts it in ``kvtpu_kvevents_dropped_total{reason="queue_full"}``.
Drop-oldest is the right policy for an ephemeral index: the newest events
describe the pod's current cache contents; stale ones were about to be
superseded anyway, and per-pod relative ordering of the surviving messages
is preserved.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    TokenProcessor,
    engine_hash_to_uint64,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    EventBatch,
    EventDecodeError,
    decode_event,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.obs.trace import (
    TRACER,
    Trace,
    current_trace,
    span as obs_span,
    use_trace,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger, trace

logger = get_logger("kvevents.pool")

# Pool lifecycle sits above the index in the lock hierarchy: a worker
# never holds the pool lock while applying into index shards, and the
# index never calls back into the pool.  Declared so both KV006 halves
# catch a future inversion (e.g. a drain that applies under _lock).
# kvlint: lock-order: Pool._lock < LRUCache._lock
lockorder.declare_order("Pool._lock", "LRUCache._lock")

# TPU pods' on-chip tier; events without an explicit medium default here
# (GPU-era fleets default to "gpu" — both score 1.0 by default).
DEFAULT_EVENT_SOURCE_DEVICE_TIER = "hbm"

_FNV32_OFFSET = 0x811C9DC5
_FNV32_PRIME = 0x01000193


def fnv1a_32(data: bytes) -> int:
    h = _FNV32_OFFSET
    for byte in data:
        h = ((h ^ byte) * _FNV32_PRIME) & 0xFFFFFFFF
    return h


@dataclass
class Message:
    """One raw event-stream message as received from a pod."""

    topic: str
    payload: bytes
    pod_identifier: str
    model_name: str
    seq: int = 0
    # Events lost to a publisher sequence gap *immediately before* this
    # message (set by the subscriber); traced messages surface it so a
    # slow/strange apply can be correlated with upstream loss.
    seq_gap: int = 0
    # Sampled ingestion trace (obs/trace.py) riding the shard queue:
    # explicit propagation across the pool's thread boundary.
    trace: Optional[Trace] = None
    enqueued_at: float = 0.0


@dataclass
class PoolConfig:
    concurrency: int = 4
    default_device_tier: str = DEFAULT_EVENT_SOURCE_DEVICE_TIER
    # Per-shard queue bound.  At the default, 4 shards hold up to 16k
    # in-flight messages (~tens of MB of msgpack) before load-shedding.
    max_queue_depth: int = 4096
    # Messages a worker drains per wake-up.  Under a backlog the whole
    # batch is decoded together and its index adds are grouped per
    # index shard before any lock is taken (``add_entries_batch``);
    # an idle stream degenerates to batch size 1 with no added
    # latency.  Observed in the kvtpu_kvevents_batch_size histogram.
    apply_batch_size: int = 32


class _BatchApplier:
    """Groups index admissions across one drained message batch.

    Engine->request mappings publish EAGERLY (``add_mappings``): later
    events in the same batch resolve their parents through
    ``index.get_request_key``, so the map must always be current.  Pod
    entry admissions DEFER and flush grouped per index shard
    (``add_entries_batch``) — one lock round-trip per shard per batch
    instead of one per key.  Evictions act as barriers (the caller
    flushes before applying one) so an add->evict pair inside a batch
    never reorders into evict->add.  Journal records for deferred adds
    are written only after their flush succeeds, preserving the "a
    failed apply is never journaled" invariant; record order matches
    digest order (per-pod order is structural: one pod -> one shard
    queue).

    Backends without the batched surface (Redis, cost-aware) fall back
    to the per-event ``add`` path transparently.
    """

    __slots__ = (
        "_index",
        "_journal",
        "_batched",
        "_adds",
        "_records",
        "_traces",
    )

    def __init__(self, index: Index, journal) -> None:
        self._index = index
        self._journal = journal
        self._batched = callable(
            getattr(index, "add_entries_batch", None)
        ) and callable(getattr(index, "add_mappings", None))
        self._adds: List[tuple] = []  # (request_keys, entries)
        self._records: List[tuple] = []  # deferred journal record args
        # Traces owning the deferred adds.  A flush failure must error
        # exactly these — a mid-batch (eviction-barrier) flush can
        # discard admissions from EARLIER messages in the batch, whose
        # traces would otherwise finish "ok" at batch end.
        self._traces: List[Trace] = []

    def add(
        self,
        pod_identifier: str,
        seq: int,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
        owner_trace: Optional[Trace] = None,
    ) -> None:
        if not self._batched:
            self._index.add(engine_keys, request_keys, entries)
            if self._journal is not None:
                self._journal.record_add(
                    pod_identifier, seq, engine_keys, request_keys, entries
                )
            return
        self._index.add_mappings(engine_keys, request_keys)
        self._adds.append((request_keys, entries))
        if owner_trace is not None:
            self._traces.append(owner_trace)
        if self._journal is not None:
            self._records.append(
                (pod_identifier, seq, engine_keys, request_keys, entries)
            )

    def flush(self) -> None:
        """Apply deferred admissions (grouped per shard), then journal
        them.  Called before any eviction and at batch end."""
        if self._adds:
            adds, self._adds = self._adds, []
            traces, self._traces = self._traces, []
            try:
                self._index.add_entries_batch(adds)
            except Exception as exc:
                # The admissions never landed: their journal records
                # must die with them, or a later flush would journal
                # operations the live index never held ("a failed
                # apply is never journaled") — and their owning traces
                # must finish errored NOW, because the batch loop only
                # sees this exception through the triggering message
                # and would finish the earlier owners "ok".
                self._records = []
                for tr in traces:
                    tr.set_error(f"batched apply flush failed: {exc!r}")
                    tr.finish("error")
                raise
        if self._records:
            records, self._records = self._records, []
            for args in records:
                self._journal.record_add(*args)


class Pool:
    """N worker threads, each draining its own FIFO queue.

    Each wake-up drains up to ``PoolConfig.apply_batch_size`` queued
    messages, decodes them together, and applies them through a
    :class:`_BatchApplier` so admissions group per index shard before
    any lock is taken.  Per-message traces, poison-pill handling, and
    per-pod ordering are unchanged from the one-message-at-a-time
    path; batch sizes land in ``kvtpu_kvevents_batch_size``.
    """

    def __init__(
        self,
        index: Index,
        token_processor: TokenProcessor,
        config: Optional[PoolConfig] = None,
        journal=None,
    ) -> None:
        self.config = config or PoolConfig()
        if self.config.concurrency <= 0:
            raise ValueError("pool concurrency must be positive")
        self._index = index
        self._token_processor = token_processor
        # Optional persistence journal (persistence.Journal), tapped
        # AFTER each index apply succeeds: the journal records applied
        # operations, so replay needs no token re-hashing and a failed
        # apply is never journaled.  Per-pod order in the journal
        # matches apply order structurally (one pod -> one shard).
        self._journal = journal
        if self.config.max_queue_depth <= 0:
            raise ValueError("pool max_queue_depth must be positive")
        self._queues: List["queue.Queue[Optional[Message]]"] = [
            queue.Queue(maxsize=self.config.max_queue_depth)
            for _ in range(self.config.concurrency)
        ]
        self._threads: List[threading.Thread] = []  # guarded-by: _lock
        self._started = False  # guarded-by: _lock
        self._lock = lockorder.tracked(threading.Lock(), "Pool._lock")

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            for i in range(self.config.concurrency):
                thread = threading.Thread(
                    target=self._worker,
                    args=(i,),
                    name=f"kvtpu-events-{i}",
                    daemon=True,
                )
                thread.start()
                self._threads.append(thread)

    def shutdown(self) -> None:
        with self._lock:
            if not self._started:
                return
            for q in self._queues:
                self._put_sentinel(q)
            for thread in self._threads:
                thread.join(timeout=10)
            self._threads.clear()
            self._started = False

    def drain(self) -> None:
        """Block until every queued message has been processed (tests)."""
        for q in self._queues:
            q.join()

    @staticmethod
    def _finish_dropped(dropped: Message, reason: str) -> None:
        """A shed message's trace must still reach the recorder: drops
        ARE the incident the flight recorder exists to explain."""
        if dropped.trace is not None:
            dropped.trace.set_error(f"dropped: {reason}")
            dropped.trace.finish("error")

    def add_task(self, message: Message) -> None:
        if message.trace is None:
            tr = TRACER.start_trace("kvevents.message")
            if tr is not None:
                tr.set_attr("pod", message.pod_identifier)
                tr.set_attr("topic", message.topic)
                tr.set_attr("seq", message.seq)
                message.trace = tr
        if message.trace is not None:
            message.enqueued_at = time.perf_counter()
        shard = fnv1a_32(message.pod_identifier.encode()) % len(self._queues)
        q = self._queues[shard]
        while True:
            try:
                q.put_nowait(message)
                return
            except queue.Full:
                pass
            # Shed the oldest queued message from this shard to admit the
            # new one (see module docstring for why drop-oldest).
            try:
                dropped = q.get_nowait()
            except queue.Empty:
                continue  # a worker drained it between put and get; retry
            q.task_done()
            if dropped is None:
                # Raced with shutdown: the popped item was the stop
                # sentinel.  Drop the NEW message instead and restore the
                # sentinel so the worker still exits.
                try:
                    q.put_nowait(None)
                except queue.Full:
                    # Never block here; the thread join in shutdown()
                    # has a timeout, so a lost sentinel only delays it.
                    logger.warning(
                        "shard %d full while restoring the shutdown "
                        "sentinel; worker exit may be delayed",
                        shard,
                    )
                METRICS.kvevents_dropped.labels(reason="shutdown").inc()
                self._finish_dropped(message, "shutdown")
                return
            METRICS.kvevents_dropped.labels(reason="queue_full").inc()
            self._finish_dropped(dropped, "queue_full")
            logger.debug(
                "event shard %d full (depth %d); dropped oldest message "
                "from pod %s",
                shard,
                self.config.max_queue_depth,
                dropped.pod_identifier,
            )

    @classmethod
    def _put_sentinel(cls, q: "queue.Queue[Optional[Message]]") -> None:
        """Enqueue the stop sentinel, shedding old messages if full."""
        while True:
            try:
                q.put_nowait(None)
                return
            except queue.Full:
                try:
                    shed = q.get_nowait()
                    q.task_done()
                    METRICS.kvevents_dropped.labels(reason="shutdown").inc()
                    if shed is not None:
                        cls._finish_dropped(shed, "shutdown")
                except queue.Empty:
                    pass

    def _worker(self, worker_index: int) -> None:
        q = self._queues[worker_index]
        batch_limit = max(1, self.config.apply_batch_size)
        while True:
            first = q.get()
            if first is None:
                q.task_done()
                return
            batch: List[Message] = [first]
            saw_sentinel = False
            # Opportunistic drain: under a backlog the worker grabs up
            # to the batch limit without blocking; an idle stream
            # processes single messages with no added latency.
            while len(batch) < batch_limit:
                try:
                    extra = q.get_nowait()
                except queue.Empty:
                    break
                if extra is None:
                    saw_sentinel = True
                    break
                batch.append(extra)
            try:
                self._process_batch(batch, worker_index)
            except Exception:
                # The batch loop guards decode and apply per message,
                # but the worker must survive ANYTHING escaping
                # (metrics observe, trace bookkeeping): a dead worker
                # means its shard's queue fills and every later event
                # for those pods is silently shed for the process
                # lifetime.
                logger.exception(
                    "event worker %d failed processing a batch; dropping",
                    worker_index,
                )
            finally:
                # task_done only after the batch (including the
                # deferred-add flush) has fully applied: drain() must
                # imply visibility.
                for _ in batch:
                    q.task_done()
                if saw_sentinel:
                    q.task_done()
            if saw_sentinel:
                return

    def _process_batch(
        self, batch: List[Message], worker_index: int
    ) -> None:
        METRICS.kvevents_batch_size.observe(len(batch))
        applier = _BatchApplier(self._index, self._journal)
        decoded: List[Optional[EventBatch]] = []
        for message in batch:
            tr = message.trace
            if tr is not None:
                # Queue wait vs apply time is the shard-health split: a
                # storm shows up as queue_wait, a stuck index backend
                # as apply.
                tr.add_completed("kvevents.queue_wait", message.enqueued_at)
                if message.seq_gap:
                    tr.set_attr("seq_gap", message.seq_gap)
            try:
                with use_trace(tr):
                    decoded.append(self._decode_message(message))
            except Exception:
                logger.exception(
                    "event worker %d failed decoding a message; dropping",
                    worker_index,
                )
                decoded.append(None)
                if tr is not None:
                    tr.finish("error")
        # Traces of successfully-digested messages stay open until the
        # final flush lands: their adds may still be deferred in the
        # applier, and a trace that reported "ok" before its admissions
        # were applied would hide a flush failure from the flight
        # recorder.
        pending_traces: List[Trace] = []
        for message, events in zip(batch, decoded):
            tr = message.trace
            if events is None:
                if tr is not None:
                    # Poison pill (error already set) or decode crash
                    # (already finished — finish() is idempotent).
                    tr.finish()
                continue
            try:
                with use_trace(tr):
                    self._apply_events(message, events, applier)
            except Exception as exc:
                if tr is not None:
                    tr.set_error(repr(exc))
                    tr.finish("error")
                logger.exception(
                    "event worker %d failed processing a message; dropping",
                    worker_index,
                )
                continue
            if tr is not None:
                pending_traces.append(tr)
        try:
            applier.flush()
        except Exception:
            logger.exception(
                "event worker %d failed flushing batched index adds; "
                "dropping the batch's deferred admissions",
                worker_index,
            )
        # The applier already finished the traces owning any discarded
        # adds as errored (whether the failing flush was this final one
        # or a mid-batch eviction barrier); for everyone else the work
        # landed, so "ok" — finish() is idempotent, first call wins.
        for tr in pending_traces:
            tr.finish()

    def _decode_message(self, message: Message) -> Optional[EventBatch]:
        with obs_span("kvevents.decode") as s:
            try:
                batch = decode_event_batch(message.payload)
            except EventDecodeError as exc:
                # Data loss, not noise: this pod's cache state is now
                # stale until its next re-store event.
                logger.warning(
                    "dropping poison-pill message from pod %s (topic %s): %s",
                    message.pod_identifier,
                    message.topic,
                    exc,
                )
                active = current_trace()
                if active is not None:
                    active.set_error(f"poison pill: {exc}")
                return None
            s.set_attr("events", len(batch.events))
        return batch

    def _apply_events(
        self,
        message: Message,
        batch: EventBatch,
        applier: _BatchApplier,
    ) -> None:
        with obs_span("kvevents.apply") as s:
            applied = 0
            for raw_event in batch.events:
                try:
                    event = decode_event(raw_event)
                except (EventDecodeError, TypeError, ValueError) as exc:
                    # Per-event skip: one malformed event must not drop
                    # the rest of the batch.
                    logger.debug("skipping undecodable event: %s", exc)
                    continue
                self._digest(message, event, applier)
                applied += 1
            s.set_attr("applied", applied)

    def _digest(
        self, message: Message, event, applier: _BatchApplier
    ) -> None:
        if isinstance(event, BlockStored):
            self._digest_block_stored(message, event, applier)
        elif isinstance(event, BlockRemoved):
            self._digest_block_removed(message, event, applier)
        elif isinstance(event, AllBlocksCleared):
            # Intentional no-op; granular BlockRemoved events follow.
            return

    def _tier(self, medium: Optional[str]) -> str:
        if medium:
            return medium.lower()
        return self.config.default_device_tier

    def _digest_block_stored(
        self, message: Message, event: BlockStored, applier: _BatchApplier
    ) -> None:
        entries = [PodEntry(message.pod_identifier, self._tier(event.medium))]

        # LoRA adapters have their own KV-incompatible hash space.
        effective_model = event.lora_name or message.model_name

        engine_keys = []
        for raw_hash in event.block_hashes:
            try:
                engine_keys.append(engine_hash_to_uint64(raw_hash))
            except (TypeError, ValueError) as exc:
                logger.debug("skipping bad block hash %r: %s", raw_hash, exc)
        if not engine_keys:
            return

        parent_request_key = EMPTY_BLOCK_HASH
        if event.parent_block_hash is not None:
            try:
                parent_engine_key = engine_hash_to_uint64(
                    event.parent_block_hash
                )
                parent_request_key = self._index.get_request_key(
                    parent_engine_key
                )
            except (TypeError, ValueError, KeyError) as exc:
                # Parent unknown (evicted or never seen): skip the event
                # rather than index keys hashed off the wrong root.
                trace(
                    logger,
                    "parent block unresolvable for pod %s: %s",
                    message.pod_identifier,
                    exc,
                )
                return

        request_keys = self._token_processor.tokens_to_kv_block_keys(
            parent_request_key, event.token_ids, effective_model
        )
        if len(request_keys) != len(engine_keys):
            logger.debug(
                "engine reported %d hashes but token ids produced %d request "
                "keys (pod %s); indexing the overlapping prefix",
                len(engine_keys),
                len(request_keys),
                message.pod_identifier,
            )
            overlap = min(len(request_keys), len(engine_keys))
            if overlap == 0:
                return
            engine_keys = engine_keys[:overlap]
            request_keys = request_keys[:overlap]

        applier.add(
            message.pod_identifier,
            message.seq,
            engine_keys,
            request_keys,
            entries,
            owner_trace=message.trace,
        )

    def _digest_block_removed(
        self, message: Message, event: BlockRemoved, applier: _BatchApplier
    ) -> None:
        # Eviction barrier: deferred adds must land first so an
        # add->evict pair inside one batch keeps its order.
        applier.flush()
        entries = [PodEntry(message.pod_identifier, self._tier(event.medium))]
        evicted_keys = []
        for raw_hash in event.block_hashes:
            try:
                engine_key = engine_hash_to_uint64(raw_hash)
            except (TypeError, ValueError) as exc:
                logger.debug("skipping bad removal hash %r: %s", raw_hash, exc)
                continue
            self._index.evict(engine_key, entries)
            evicted_keys.append(engine_key)
        if self._journal is not None and evicted_keys:
            self._journal.record_evict(
                message.pod_identifier, message.seq, evicted_keys, entries
            )
