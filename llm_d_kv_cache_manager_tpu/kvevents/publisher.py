"""ZMQ PUB helper publishing KVEvent batches the way engine pods do.

Used by demos and tests to simulate a fleet (reference pattern:
examples/helper/publisher.go:57-84).  Message = 3 parts:
``[topic, seq (u64 BE), msgpack(EventBatch)]``, topic
``kv@<pod-id>@<model>``.

**Lock discipline** (docs/event-plane.md): the seq lock covers ONLY
sequence assignment + enqueueing the encoded frame onto the send
queue; the socket send happens outside it, serialized by a separate
send lock draining the queue in FIFO (= seq) order.  Concurrent
publishers therefore never serialize on socket I/O — only on the
O(1) seq bump — while the wire still carries strictly increasing
seqs in order (the subscriber-side tracker sees no phantom
gaps/restarts).

**Coalescing** (``KVEVENTS_COALESCE_EVENTS`` / ``KVEVENTS_COALESCE_MS``,
or the constructor args): adjacent events from successive ``publish``
calls are buffered and shipped as ONE wire batch — one topic frame,
one seq, one msgpack envelope — shrinking the subscriber's per-message
demux + decode work at the source.  Events keep their identity inside
the batch (the pool digests them one by one, in order), so index
state, journal records, and seq/gap classification are bit-identical
to the uncoalesced stream — the parity the write-path tests pin.  A
buffered ``publish`` returns None; the flushing call (buffer full,
window elapsed, or explicit :meth:`flush`/:meth:`close`) returns the
seq the merged batch used; a background flusher bounds the age of a
trailing buffer when the producer goes idle (~2x the window).
"""

from __future__ import annotations

import os
import struct
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

import zmq

from llm_d_kv_cache_manager_tpu.kvevents.events import EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import TOPIC_PREFIX
from llm_d_kv_cache_manager_tpu.utils import lockorder

# close() holds the send lock (no send may be mid-flight when the
# socket dies) and then the seq lock (no enqueue may race the closed
# flag); publish never nests them the other way — it releases the seq
# lock before draining sends.
# kvlint: lock-order: Publisher._send_lock < Publisher._lock
lockorder.declare_order("Publisher._send_lock", "Publisher._lock")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class Publisher:
    def __init__(
        self,
        endpoint: str,
        pod_identifier: str,
        model_name: str,
        bind: bool = True,
        context: Optional[zmq.Context] = None,
        coalesce_events: Optional[int] = None,
        coalesce_ms: Optional[float] = None,
    ) -> None:
        self.pod_identifier = pod_identifier
        self.model_name = model_name
        self._context = context or zmq.Context.instance()
        self._socket = self._context.socket(zmq.PUB)
        self._socket.setsockopt(zmq.LINGER, 0)
        if bind:
            self._socket.bind(endpoint)
        else:
            self._socket.connect(endpoint)
        # Seq assignment + send-queue enqueue must be one atomic step:
        # two threads interleaving `_seq += 1` with their enqueues
        # would queue seqs out of order, which the subscriber-side
        # tracker reads as gaps/restarts that never happened.  The
        # actual socket send happens OUTSIDE this lock (see module
        # docstring).
        self._lock = lockorder.tracked(threading.Lock(), "Publisher._lock")
        self._seq = 0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        # Encoded frames awaiting send, in seq order.  Deliberately NOT
        # single-lock guarded: appends happen under _lock (so FIFO
        # order IS seq order) while pops happen under _send_lock (one
        # drainer at a time keeps the wire ordered); deque append and
        # popleft are individually atomic, which is all the two-lock
        # discipline needs.
        self._pending: Deque[List[bytes]] = deque()
        self._send_lock = lockorder.tracked(
            threading.Lock(), "Publisher._send_lock"
        )
        # Coalescing buffer (None -> env; 0/1 disables).
        if coalesce_events is None:
            coalesce_events = _env_int("KVEVENTS_COALESCE_EVENTS", 0)
        if coalesce_ms is None:
            coalesce_ms = _env_float("KVEVENTS_COALESCE_MS", 2.0)
        self._coalesce_max = max(0, coalesce_events)
        self._coalesce_window_s = max(0.0, coalesce_ms) / 1000.0
        self._buffer: List[object] = []  # guarded-by: _lock
        self._buffer_since = 0.0  # guarded-by: _lock
        # Age-bound enforcement for an IDLE producer: publish() flushes
        # a stale buffer inline, but a trailing sub-max batch would
        # otherwise sit unsent forever — invisible staleness with no
        # seq gap to trigger resync.  A tiny daemon flusher (only when
        # coalescing is on) bounds it at ~2x the window.
        self._flusher_stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None
        if self._coalesce_max > 1:
            self._flusher = threading.Thread(
                target=self._flush_loop,
                name=f"kvtpu-pub-flush-{pod_identifier}",
                daemon=True,
            )
            self._flusher.start()

    @property
    def topic(self) -> str:
        return f"{TOPIC_PREFIX}{self.pod_identifier}@{self.model_name}"

    @property
    def endpoint(self) -> str:
        """The actual endpoint, post-bind — with the OS-assigned port when
        bound to port 0 (lets tests avoid fixed-port flakes)."""
        return self._socket.getsockopt(zmq.LAST_ENDPOINT).decode()

    @property
    def port(self) -> int:
        return int(self.endpoint.rsplit(":", 1)[1])

    def _enqueue_locked(self, events: Tuple[object, ...]) -> int:
        """Assign the next seq and queue the encoded frame; caller
        holds ``_lock``."""
        batch = EventBatch(ts=time.time(), events=list(events))
        payload = batch.encode()
        self._seq += 1
        seq = self._seq
        self._pending.append(
            [self.topic.encode(), struct.pack(">Q", seq), payload]
        )
        return seq

    def _drain_sends(self) -> None:
        """Send queued frames in FIFO order.  One drainer at a time;
        a caller returning from here is guaranteed every frame it
        enqueued beforehand has been sent (by itself or by the drainer
        it waited on)."""
        with self._send_lock:
            while True:
                try:
                    parts = self._pending.popleft()
                except IndexError:
                    return
                self._socket.send_multipart(parts)

    def publish(self, *events) -> Optional[int]:
        """Publish events; returns the seq of the wire batch they rode,
        or None when coalescing buffered them for a later flush.

        Thread-safe: concurrent publishers (fleet simulators drive one
        Publisher from several threads) get strictly increasing seqs
        with sends in seq order."""
        with self._lock:
            if self._closed:
                raise zmq.ZMQError(zmq.ENOTSOCK, "publisher is closed")
            if self._coalesce_max > 1:
                now = time.monotonic()
                if not self._buffer:
                    self._buffer_since = now
                self._buffer.extend(events)
                if (
                    len(self._buffer) < self._coalesce_max
                    and now - self._buffer_since < self._coalesce_window_s
                ):
                    return None
                merged, self._buffer = tuple(self._buffer), []
                seq = self._enqueue_locked(merged)
            else:
                seq = self._enqueue_locked(events)
        self._drain_sends()
        return seq

    def flush(self) -> Optional[int]:
        """Ship any coalescing-buffered events now; returns the seq
        used, or None when the buffer was empty."""
        with self._lock:
            if self._closed or not self._buffer:
                return None
            merged, self._buffer = tuple(self._buffer), []
            seq = self._enqueue_locked(merged)
        self._drain_sends()
        return seq

    def _flush_loop(self) -> None:
        interval = max(self._coalesce_window_s, 0.001)
        while not self._flusher_stop.wait(interval):
            stale_seq = None
            with self._lock:
                if self._closed:
                    return
                if self._buffer and (
                    time.monotonic() - self._buffer_since
                    >= self._coalesce_window_s
                ):
                    merged, self._buffer = tuple(self._buffer), []
                    stale_seq = self._enqueue_locked(merged)
            if stale_seq is not None:
                self._drain_sends()

    def advance_seq(self, count: int = 1) -> int:
        """Skip ``count`` sequence numbers WITHOUT sending — a test/bench
        hook that makes the next publish look like ``count`` lost events
        (forces a subscriber-side gap deterministically)."""
        with self._lock:
            self._seq += count
            return self._seq

    def close(self) -> None:
        """Flush buffered events + queued sends, then close the socket.
        The buffer flush happens INSIDE the locked section — a
        flush-then-lock sequence would let a concurrent publish buffer
        an event between the two and lose it silently.  Holding the
        send lock across the close keeps a concurrent publisher's
        drain from racing the socket teardown."""
        with self._send_lock:
            with self._lock:
                if self._closed:
                    return
                self._closed = True
                if self._buffer:
                    merged, self._buffer = tuple(self._buffer), []
                    self._enqueue_locked(merged)
                pending, self._pending = list(self._pending), deque()
            for parts in pending:
                self._socket.send_multipart(parts)
            self._socket.close()
        self._flusher_stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._flusher = None
