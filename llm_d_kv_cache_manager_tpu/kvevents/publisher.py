"""ZMQ PUB helper publishing KVEvent batches the way engine pods do.

Used by demos and tests to simulate a fleet (reference pattern:
examples/helper/publisher.go:57-84).  Message = 3 parts:
``[topic, seq (u64 BE), msgpack(EventBatch)]``, topic
``kv@<pod-id>@<model>``.
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Optional

import zmq

from llm_d_kv_cache_manager_tpu.kvevents.events import EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import TOPIC_PREFIX
from llm_d_kv_cache_manager_tpu.utils import lockorder


class Publisher:
    def __init__(
        self,
        endpoint: str,
        pod_identifier: str,
        model_name: str,
        bind: bool = True,
        context: Optional[zmq.Context] = None,
    ) -> None:
        self.pod_identifier = pod_identifier
        self.model_name = model_name
        self._context = context or zmq.Context.instance()
        self._socket = self._context.socket(zmq.PUB)
        self._socket.setsockopt(zmq.LINGER, 0)
        if bind:
            self._socket.bind(endpoint)
        else:
            self._socket.connect(endpoint)
        # Seq assignment + send must be one atomic step: two threads
        # interleaving `_seq += 1` with their sends would publish seqs
        # out of order (or duplicated), which the subscriber-side
        # tracker reads as gaps/restarts that never happened.  Leaf
        # lock — nothing else is acquired under it.
        self._lock = lockorder.tracked(threading.Lock(), "Publisher._lock")
        self._seq = 0  # guarded-by: _lock

    @property
    def topic(self) -> str:
        return f"{TOPIC_PREFIX}{self.pod_identifier}@{self.model_name}"

    @property
    def endpoint(self) -> str:
        """The actual endpoint, post-bind — with the OS-assigned port when
        bound to port 0 (lets tests avoid fixed-port flakes)."""
        return self._socket.getsockopt(zmq.LAST_ENDPOINT).decode()

    @property
    def port(self) -> int:
        return int(self.endpoint.rsplit(":", 1)[1])

    def publish(self, *events) -> int:
        """Publish events as one batch; returns the sequence number used.

        Thread-safe: concurrent publishers (fleet simulators drive one
        Publisher from several threads) get strictly increasing seqs
        with sends in seq order."""
        batch = EventBatch(ts=time.time(), events=list(events))
        payload = batch.encode()
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._socket.send_multipart(
                [
                    self.topic.encode(),
                    struct.pack(">Q", seq),
                    payload,
                ]
            )
        return seq

    def advance_seq(self, count: int = 1) -> int:
        """Skip ``count`` sequence numbers WITHOUT sending — a test/bench
        hook that makes the next publish look like ``count`` lost events
        (forces a subscriber-side gap deterministically)."""
        with self._lock:
            self._seq += count
            return self._seq

    def close(self) -> None:
        # Same lock as publish(): closing mid-send would raise
        # zmq.ZMQError in whichever simulator thread held the socket.
        with self._lock:
            self._socket.close()
