"""Gap-driven anti-entropy resync: suspect pods, inventory pulls, repair.

The wire-level sequence numbers the subscriber parses finally close
their loop here.  A detected gap means events were lost: the index's
claims about that pod are now *suspect* — it may advertise blocks the
pod evicted (stale hits mis-route traffic) or miss blocks the pod
stored (lost hit rate).  Instead of silently serving stale scores until
LRU churn clears them, the gap listener marks the pod suspect and this
manager repairs it:

1. **mark** — ``mark_suspect(pod, model)`` (wired as the
   ``SubscriberManager`` gap listener) records the pod with a
   timestamp and bumps ``kvtpu_kvevents_suspect_pods``; marking is
   idempotent while a pod is already suspect.
2. **fetch** — the worker thread pulls a block-inventory snapshot
   through the pluggable :class:`InventorySource` (span
   ``kvevents.resync.fetch``), with bounded retries and backoff.
3. **repair** — the inventory is handed to the ingestion pool as a
   :class:`~.pool.ResyncJob` riding the pod's normal shard lane, so the
   purge + re-apply is ordered against the pod's live events and runs
   through the same batched-apply surface (span
   ``kvevents.resync.apply`` on the worker side).
4. **report** — on success the pod leaves the suspect set and the
   mark→repair **staleness window** lands in
   ``kvtpu_kvevents_resync_staleness_seconds``; outcomes count in
   ``kvtpu_kvevents_resyncs_total{outcome=ok|failed}``.  A failed
   resync leaves the pod suspect (visible on the gauge) until the next
   gap or an explicit ``request_resync``.

Inventory sources are deliberately pluggable: production fleets expose
per-pod block inventories in different ways (a vLLM debug endpoint, a
shared-storage manifest, a scheduler-side mirror).
:class:`CallableInventorySource` adapts any ``fn(pod) ->
PodInventory | None``; :class:`EmptyInventorySource` is the degraded
mode for fleets with no inventory surface at all — the "snapshot" is
empty, so a gap simply *purges* the pod's suspect entries (stale
claims stop attracting traffic; the live stream re-stores reality).
"""

from __future__ import annotations

import threading
import time
from abc import ABC, abstractmethod
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored
from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, ResyncJob
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER, span as obs_span
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("kvevents.resync")


@dataclass
class InventoryBlock:
    """One stored-block record of a pod's inventory snapshot, shaped
    like the ``BlockStored`` wire event it replays as.  Records must be
    listed in parent-chain order (parents before children), exactly as
    the engine originally published them."""

    block_hashes: List[object]
    token_ids: List[int]
    block_size: int
    parent_block_hash: Optional[object] = None
    medium: Optional[str] = None
    lora_name: Optional[str] = None


@dataclass
class PodInventory:
    """A pod's current block inventory, as pulled from an
    :class:`InventorySource`."""

    pod_identifier: str
    model_name: str
    blocks: List[InventoryBlock] = field(default_factory=list)


class InventorySource(ABC):
    """Where pod block-inventory snapshots come from (pluggable)."""

    @abstractmethod
    def fetch_inventory(self, pod_identifier: str) -> Optional[PodInventory]:
        """Return the pod's inventory, or None when unavailable (the
        resync retries, then fails leaving the pod suspect).  Called
        from the resync worker thread; may block on I/O."""


class CallableInventorySource(InventorySource):
    """Adapts a plain ``fn(pod_identifier) -> PodInventory | None``
    (tests, benches, scheduler-side mirrors)."""

    def __init__(
        self, fn: Callable[[str], Optional[PodInventory]]
    ) -> None:
        self._fn = fn

    def fetch_inventory(self, pod_identifier: str) -> Optional[PodInventory]:
        return self._fn(pod_identifier)


class EmptyInventorySource(InventorySource):
    """Degraded mode for fleets with no inventory surface: every fetch
    returns an empty snapshot, so a resync purges the pod's suspect
    index entries and lets the live event stream re-store reality.
    Strictly better than serving stale claims, at the cost of a
    temporary hit-rate dip for that pod."""

    def fetch_inventory(self, pod_identifier: str) -> Optional[PodInventory]:
        return PodInventory(pod_identifier=pod_identifier, model_name="")


@dataclass
class ResyncConfig:
    # Inventory-fetch attempts per resync before giving up (the pod
    # stays suspect).
    max_attempts: int = 3
    retry_backoff_s: float = 1.0
    # Bound on how long the manager waits for the pool worker to apply
    # a queued ResyncJob before counting the resync failed.
    apply_timeout_s: float = 30.0


class ResyncManager:
    """Suspect-pod registry + one repair worker thread.

    ``mark_suspect`` is safe to call from poller threads (it only flips
    registry state and notifies); all I/O happens on the worker.
    """

    def __init__(
        self,
        pool: Pool,
        source: InventorySource,
        config: Optional[ResyncConfig] = None,
    ) -> None:
        self._pool = pool
        self._source = source
        self.config = config or ResyncConfig()
        # Leaf lock + wake channel in one Condition (the StagingBudget
        # shape — tracking the inner lock would trip the watchdog on
        # Condition's ownership probe).  Nothing else is acquired under
        # it: the worker fetches and enqueues with it released.
        self._lock = lockorder.tracked(
            threading.Condition(), "ResyncManager._lock"
        )
        # pod -> perf_counter() of the FIRST gap since it was last
        # clean; preserved across repeat gaps so the staleness window
        # measures mark -> repaired, not last-gap -> repaired.
        self._suspect: Dict[str, float] = {}  # guarded-by: _lock
        self._model_by_pod: Dict[str, str] = {}  # guarded-by: _lock
        self._queue: Deque[str] = deque()  # guarded-by: _lock
        self._queued: set = set()  # guarded-by: _lock
        self._stopping = False  # guarded-by: _lock
        self._resyncs_ok = 0  # guarded-by: _lock
        self._resyncs_failed = 0  # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None

    # -- marking (poller-thread safe) -----------------------------------

    def gap_listener(self, pod_identifier: str, topic: str, gap: int) -> None:
        """``SubscriberManager(on_gap=...)`` adapter: a wire-level seq
        gap marks the pod suspect and schedules a resync."""
        self.mark_suspect(pod_identifier)

    def mark_suspect(
        self, pod_identifier: str, model_name: str = ""
    ) -> bool:
        """Record a pod as suspect and queue a resync; returns True if
        the pod was newly marked (False: already suspect/queued)."""
        with self._lock:
            if self._stopping:
                return False
            newly = pod_identifier not in self._suspect
            if newly:
                self._suspect[pod_identifier] = time.perf_counter()
            if model_name:
                self._model_by_pod[pod_identifier] = model_name
            if pod_identifier not in self._queued:
                self._queued.add(pod_identifier)
                self._queue.append(pod_identifier)
                self._lock.notify_all()
            suspects = len(self._suspect)
        METRICS.kvevents_suspect_pods.set(suspects)
        if newly:
            logger.warning(
                "pod %s marked suspect (sequence gap); resync scheduled",
                pod_identifier,
            )
        return newly

    # Back-compat/explicit trigger with the ISSUE's vocabulary.
    def request_resync(
        self, pod_identifier: str, model_name: str = ""
    ) -> bool:
        return self.mark_suspect(pod_identifier, model_name)

    def suspect_pods(self) -> List[str]:
        with self._lock:
            return sorted(self._suspect)

    def is_suspect(self, pod_identifier: str) -> bool:
        with self._lock:
            return pod_identifier in self._suspect

    def stats(self) -> dict:
        with self._lock:
            return {
                "suspect": sorted(self._suspect),
                "queued": len(self._queue),
                "resyncs_ok": self._resyncs_ok,
                "resyncs_failed": self._resyncs_failed,
            }

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        with self._lock:
            self._stopping = False
        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=self._run, name="kvtpu-evplane-resync", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        with self._lock:
            self._stopping = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None

    # -- worker ----------------------------------------------------------

    def _next_pod(self) -> Optional[str]:
        with self._lock:
            while not self._queue and not self._stopping:
                self._lock.wait()
            if self._stopping:
                return None
            pod = self._queue.popleft()
            self._queued.discard(pod)
            return pod

    def _run(self) -> None:
        while True:
            pod = self._next_pod()
            if pod is None:
                return
            try:
                self._resync_pod(pod)
            except Exception:  # noqa: BLE001 — worker must survive
                logger.exception("resync worker failed for pod %s", pod)
                self._record_outcome(pod, ok=False)

    def _fetch(self, pod: str) -> Optional[PodInventory]:
        for attempt in range(1, self.config.max_attempts + 1):
            with self._lock:
                if self._stopping:
                    return None
            try:
                inventory = self._source.fetch_inventory(pod)
            except Exception as exc:  # noqa: BLE001 — source may do I/O
                inventory = None
                logger.warning(
                    "inventory fetch for pod %s failed (attempt %d/%d): %s",
                    pod,
                    attempt,
                    self.config.max_attempts,
                    exc,
                )
            if inventory is not None:
                return inventory
            if attempt < self.config.max_attempts:
                time.sleep(self.config.retry_backoff_s * attempt)
        return None

    def _resync_pod(self, pod: str) -> None:
        with self._lock:
            suspect_since = self._suspect.get(pod, time.perf_counter())
            model_name = self._model_by_pod.get(pod, "")
        tr = TRACER.start_trace("kvevents.resync")
        if tr is not None:
            tr.set_attr("pod", pod)
        with obs_span("kvevents.resync.fetch") if tr is None else tr.span(
            "kvevents.resync.fetch"
        ):
            inventory = self._fetch(pod)
        if inventory is None:
            logger.warning(
                "resync for pod %s failed: no inventory after %d attempts; "
                "pod stays suspect",
                pod,
                self.config.max_attempts,
            )
            if tr is not None:
                tr.set_error("inventory unavailable")
                tr.finish("error")
            self._record_outcome(pod, ok=False)
            return

        # The job's completion is reported by the pool worker that
        # applies it (ordered within the pod's shard lane); bounded
        # wait here.
        done = threading.Event()
        outcome = {}

        def on_done(job: ResyncJob, ok: bool, purged: int, detail: str):
            outcome["ok"] = ok
            outcome["purged"] = purged
            outcome["detail"] = detail
            done.set()

        job = ResyncJob(
            pod_identifier=pod,
            model_name=inventory.model_name or model_name,
            events=[
                BlockStored(
                    block_hashes=list(block.block_hashes),
                    parent_block_hash=block.parent_block_hash,
                    token_ids=list(block.token_ids),
                    block_size=block.block_size,
                    medium=block.medium,
                    lora_name=block.lora_name,
                )
                for block in inventory.blocks
            ],
            suspect_since=suspect_since,
            on_done=on_done,
        )
        self._pool.enqueue_resync(job, trace_=tr)
        if not done.wait(self.config.apply_timeout_s):
            logger.warning(
                "resync apply for pod %s timed out after %.0fs; pod stays "
                "suspect",
                pod,
                self.config.apply_timeout_s,
            )
            self._record_outcome(pod, ok=False)
            return
        if not outcome.get("ok"):
            self._record_outcome(pod, ok=False)
            return
        staleness = time.perf_counter() - suspect_since
        METRICS.kvevents_resync_staleness.observe(staleness)
        logger.info(
            "pod %s resynced: purged %d entries, re-applied %d inventory "
            "blocks, staleness window %.3fs",
            pod,
            outcome.get("purged", 0),
            len(inventory.blocks),
            staleness,
        )
        self._record_outcome(pod, ok=True)

    def _record_outcome(self, pod: str, ok: bool) -> None:
        with self._lock:
            if ok:
                self._resyncs_ok += 1
                self._suspect.pop(pod, None)
            else:
                self._resyncs_failed += 1
            suspects = len(self._suspect)
        METRICS.kvevents_resyncs.labels(
            outcome="ok" if ok else "failed"
        ).inc()
        METRICS.kvevents_suspect_pods.set(suspects)
