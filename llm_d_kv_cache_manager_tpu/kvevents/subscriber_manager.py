"""Tracks one ZMQ subscriber per live engine pod.

Idempotent ``ensure_subscriber``; an endpoint change (pod rescheduled with a
new IP) restarts the subscriber; ``remove_subscriber`` on pod death; full
``shutdown``.  Driven by pod-discovery (the k8s reconciler adapter) or
manually in tests/demos.  (Capability parity:
pkg/kvevents/subscriber_manager.go.)
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from llm_d_kv_cache_manager_tpu.utils import lockorder

import zmq

from llm_d_kv_cache_manager_tpu.kvevents.pool import Message
from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
    ZMQSubscriber,
    ZMQSubscriberConfig,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("kvevents.subscriber_manager")


class SubscriberManager:
    def __init__(
        self,
        sink: Callable[[Message], None],
        context: Optional[zmq.Context] = None,
        bind: bool = False,
    ) -> None:
        self._sink = sink
        self._context = context
        self._bind = bind
        # Subscriber stop()/join() happens OUTSIDE this lock (a wedged
        # close must not stall reconciliation), so it stays a leaf.
        self._lock = lockorder.tracked(
            threading.Lock(), "SubscriberManager._lock"
        )
        self._subscribers: Dict[str, ZMQSubscriber] = {}  # guarded-by: _lock

    def ensure_subscriber(
        self,
        pod_identifier: str,
        endpoint: str,
        topic_filter: Optional[str] = None,
    ) -> bool:
        """Start (or restart on endpoint/filter change) a subscriber.

        ``topic_filter=None`` subscribes to ``kv@<pod_identifier>@`` only;
        pass ``"kv@"`` when the subscriber identity differs from the
        engine's published pod id (scheduler-plugin discovery, global
        socket mode — reference: EnsureSubscriber's topicFilter arg).
        Returns True if a new subscriber was started.
        """
        stale: Optional[ZMQSubscriber] = None
        with self._lock:
            existing = self._subscribers.get(pod_identifier)
            if existing is not None:
                if (
                    existing.config.endpoint == endpoint
                    and existing.config.topic_filter == topic_filter
                ):
                    return False
                logger.info(
                    "subscription change for pod %s: endpoint %s -> %s, "
                    "topic filter %r -> %r; restarting",
                    pod_identifier,
                    existing.config.endpoint,
                    endpoint,
                    existing.config.topic_filter,
                    topic_filter,
                )
                stale = existing
                del self._subscribers[pod_identifier]

            subscriber = ZMQSubscriber(
                ZMQSubscriberConfig(
                    endpoint=endpoint,
                    pod_identifier=pod_identifier,
                    topic_filter=topic_filter,
                    bind=self._bind,
                ),
                self._sink,
                context=self._context,
            )
            subscriber.start()
            self._subscribers[pod_identifier] = subscriber
            logger.info(
                "subscribed to pod %s at %s", pod_identifier, endpoint
            )
        # Join the stale subscriber's thread outside the lock: a wedged
        # close must not stall fleet-wide reconciliation.
        if stale is not None:
            stale.stop()
        return True

    def remove_subscriber(self, pod_identifier: str) -> bool:
        with self._lock:
            subscriber = self._subscribers.pop(pod_identifier, None)
        if subscriber is None:
            return False
        subscriber.stop()
        logger.info("unsubscribed from pod %s", pod_identifier)
        return True

    def active_pods(self) -> list:
        with self._lock:
            return sorted(self._subscribers)

    def shutdown(self) -> None:
        with self._lock:
            subscribers = list(self._subscribers.values())
            self._subscribers.clear()
        for subscriber in subscribers:
            subscriber.stop()
