"""Registry of per-pod event subscriptions over the consolidated poller.

Where this class used to spawn one ``ZMQSubscriber`` thread per pod, it
is now a *registry*: ``ensure_subscriber`` attaches a pod's SUB-socket
channel to the shared :class:`~.poller.PollerPool` (a fixed pool of
``KVEVENTS_POLLERS`` threads multiplexing the whole fleet), an endpoint
change detaches the stale channel and attaches a fresh one, and
``remove_subscriber``/``shutdown`` detach cleanly.  Thread count is
O(pollers), not O(pods) — see docs/event-plane.md.

Semantics preserved from the thread-per-pod era: ``ensure_subscriber``
is idempotent; an endpoint change (pod rescheduled with a new IP)
restarts the subscription; driven by pod-discovery (the k8s reconciler
adapter) or manually in tests/demos.  (Capability parity:
pkg/kvevents/subscriber_manager.go.)

A detached channel stops delivering *immediately* (the poller checks
the channel's ``detached`` flag before every sink call); its socket is
closed by the owning poller within one poll interval.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from llm_d_kv_cache_manager_tpu.utils import lockorder

import zmq

from llm_d_kv_cache_manager_tpu.kvevents.pool import Message
from llm_d_kv_cache_manager_tpu.kvevents.poller import (
    Channel,
    ChannelConfig,
    PollerPool,
    PollerPoolConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import GapListener
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("kvevents.subscriber_manager")

# The registry lock wraps channel attach/detach, which take the poller
# pool's lifecycle lock and the target poller's command lock (both
# leaves: nothing is acquired under them).  Declared so an inversion —
# e.g. a poller callback reaching back into the registry — trips both
# kvlint KV006 and the runtime watchdog.
# kvlint: lock-order: SubscriberManager._lock < PollerPool._lock
lockorder.declare_order("SubscriberManager._lock", "PollerPool._lock")
# kvlint: lock-order: SubscriberManager._lock < Poller._cmd_lock
lockorder.declare_order("SubscriberManager._lock", "Poller._cmd_lock")


class SubscriberManager:
    def __init__(
        self,
        sink: Callable[[Message], None],
        context: Optional[zmq.Context] = None,
        bind: bool = False,
        pollers: Optional[int] = None,
        poll_interval_ms: Optional[int] = None,
        on_gap: Optional[GapListener] = None,
        sink_batch: Optional[Callable[[list], None]] = None,
    ) -> None:
        self._sink = sink
        # Batched delivery (``Pool.add_tasks``): a poller hands each
        # socket burst to this in ONE call — the write-path fast
        # lane's enqueue half (docs/event-plane.md).  None keeps
        # per-message delivery through ``sink``.
        self._sink_batch = sink_batch
        self._bind = bind
        # Sequence-gap listener plumbed into every channel's demux —
        # the resync manager's mark_suspect in production
        # (docs/event-plane.md).
        self._on_gap = on_gap
        self._pool = PollerPool(
            context=context,
            config=PollerPoolConfig(
                pollers=pollers, poll_interval_ms=poll_interval_ms
            ),
        )
        # Registry lock is a leaf: channel detach is flag-flip cheap
        # (no thread join anymore), but poller-pool shutdown still
        # happens OUTSIDE it.
        self._lock = lockorder.tracked(
            threading.Lock(), "SubscriberManager._lock"
        )
        self._channels: Dict[str, Channel] = {}  # guarded-by: _lock
        self._shutdown = False  # guarded-by: _lock

    def ensure_subscriber(
        self,
        pod_identifier: str,
        endpoint: str,
        topic_filter: Optional[str] = None,
    ) -> bool:
        """Attach (or re-attach on endpoint/filter change) a pod channel.

        ``topic_filter=None`` subscribes to ``kv@<pod_identifier>@`` only;
        pass ``"kv@"`` when the subscriber identity differs from the
        engine's published pod id (scheduler-plugin discovery, global
        socket mode — reference: EnsureSubscriber's topicFilter arg).
        Returns True if a new subscription was started.
        """
        with self._lock:
            if self._shutdown:
                return False
            existing = self._channels.get(pod_identifier)
            if existing is not None:
                if (
                    existing.config.endpoint == endpoint
                    and existing.config.topic_filter == topic_filter
                ):
                    return False
                logger.info(
                    "subscription change for pod %s: endpoint %s -> %s, "
                    "topic filter %r -> %r; reattaching",
                    pod_identifier,
                    existing.config.endpoint,
                    endpoint,
                    existing.config.topic_filter,
                    topic_filter,
                )
                self._pool.detach(existing)
                del self._channels[pod_identifier]

            channel = self._pool.attach(
                ChannelConfig(
                    endpoint=endpoint,
                    pod_identifier=pod_identifier,
                    topic_filter=topic_filter,
                    bind=self._bind,
                ),
                self._sink,
                on_gap=self._on_gap,
                sink_batch=self._sink_batch,
            )
            self._channels[pod_identifier] = channel
            logger.info(
                "subscribed to pod %s at %s (poller %d)",
                pod_identifier,
                endpoint,
                channel.poller_index,
            )
        return True

    def remove_subscriber(self, pod_identifier: str) -> bool:
        with self._lock:
            channel = self._channels.pop(pod_identifier, None)
            if channel is None:
                return False
            self._pool.detach(channel)
        logger.info("unsubscribed from pod %s", pod_identifier)
        return True

    def active_pods(self) -> list:
        with self._lock:
            return sorted(self._channels)

    def gap_count(self, pod_identifier: str) -> int:
        """Events lost to sequence gaps on this pod's live channel."""
        with self._lock:
            channel = self._channels.get(pod_identifier)
            return channel.tracker.gap_count if channel else 0

    def restart_count(self, pod_identifier: str) -> int:
        """Publisher restarts observed on this pod's live channel."""
        with self._lock:
            channel = self._channels.get(pod_identifier)
            return channel.tracker.restart_count if channel else 0

    def poller_count(self) -> int:
        return self._pool.poller_count()

    def shutdown(self) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            channels = list(self._channels.values())
            self._channels.clear()
        for channel in channels:
            self._pool.detach(channel)
        # Poller join happens outside the registry lock: a wedged
        # poller must not stall fleet-wide reconciliation.
        self._pool.shutdown()
