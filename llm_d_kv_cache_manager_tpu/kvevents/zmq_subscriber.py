"""ZMQ SUB subscriber for one engine pod's KV-event stream.

Wire format (reference: pkg/kvevents/zmq_subscriber.go:135-155, matching
vLLM's event publisher): 3-part messages ``[topic, seq, payload]`` where
``topic = "kv@<pod-id>@<model>"``, ``seq`` is a big-endian uint64, and
``payload`` is a msgpack ``EventBatch``.

Lifecycle: a dedicated thread polls with a short timeout so cancellation is
responsive; socket errors tear the socket down and reconnect after a
backoff.  Subscribers tolerate absent publishers (ZMQ connects lazily), so
the fleet can be simulated — or slow to start — without errors.

Sequence numbers are parsed and surfaced for gap detection.  The reference
leaves them unused (zmq_subscriber.go:143, a noted improvement
opportunity); here a gap increments a counter and logs, giving operators a
signal that events were lost and scores may be stale until re-store.
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass
from typing import Callable, Optional

import zmq

from llm_d_kv_cache_manager_tpu.kvevents.pool import Message
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger, trace

logger = get_logger("kvevents.zmq")

TOPIC_PREFIX = "kv@"
POLL_INTERVAL_MS = 250
RECONNECT_BACKOFF_SECONDS = 5.0


def parse_topic(topic: str) -> Optional[tuple]:
    """``kv@<pod-id>@<model>`` -> (pod_id, model); None if malformed.

    Model names may themselves contain ``@`` (LoRA refs); split only twice.
    """
    if not topic.startswith(TOPIC_PREFIX):
        return None
    rest = topic[len(TOPIC_PREFIX):]
    pod_id, sep, model = rest.partition("@")
    if not sep or not pod_id or not model:
        return None
    return pod_id, model


@dataclass
class ZMQSubscriberConfig:
    endpoint: str
    pod_identifier: str
    # Subscribe to this pod's topics only; "" subscribes to everything.
    topic_filter: Optional[str] = None
    # bind=True for local test endpoints, connect for remote pods
    # (reference: zmq_subscriber.go:92-105).
    bind: bool = False


class ZMQSubscriber:
    """One SUB socket + polling thread feeding a message sink."""

    def __init__(
        self,
        config: ZMQSubscriberConfig,
        sink: Callable[[Message], None],
        context: Optional[zmq.Context] = None,
    ) -> None:
        self.config = config
        self._sink = sink
        self._context = context or zmq.Context.instance()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Sequence numbers are independent per topic (model/LoRA streams
        # from one pod each number from their own counter).
        self._last_seq_by_topic: dict = {}
        self.gap_count = 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run,
            name=f"kvtpu-zmq-{self.config.pod_identifier}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _topic_filter(self) -> bytes:
        if self.config.topic_filter is not None:
            return self.config.topic_filter.encode()
        return f"{TOPIC_PREFIX}{self.config.pod_identifier}@".encode()

    def _open_socket(self) -> zmq.Socket:
        sock = self._context.socket(zmq.SUB)
        sock.setsockopt(zmq.LINGER, 0)
        sock.setsockopt(zmq.SUBSCRIBE, self._topic_filter())
        if self.config.bind:
            sock.bind(self.config.endpoint)
        else:
            sock.connect(self.config.endpoint)
        return sock

    def _run(self) -> None:
        while not self._stop.is_set():
            sock = None
            try:
                sock = self._open_socket()
                self._poll_loop(sock)
            except Exception as exc:  # noqa: BLE001 — thread must survive
                logger.warning(
                    "subscriber for %s errored (%s); reconnecting in %.0fs",
                    self.config.pod_identifier,
                    exc,
                    RECONNECT_BACKOFF_SECONDS,
                )
                self._stop.wait(RECONNECT_BACKOFF_SECONDS)
            finally:
                if sock is not None:
                    sock.close()

    def _poll_loop(self, sock: zmq.Socket) -> None:
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)
        while not self._stop.is_set():
            if not dict(poller.poll(POLL_INTERVAL_MS)):
                continue
            parts = sock.recv_multipart()
            message = self._parse_message(parts)
            if message is None:
                continue
            try:
                self._sink(message)
            except Exception:  # noqa: BLE001 — sink bugs must not kill us
                logger.exception(
                    "sink failed for a message from %s; dropping it",
                    self.config.pod_identifier,
                )

    def _parse_message(self, parts) -> Optional[Message]:
        # Dropped frames are event loss (stale scores for that pod
        # until re-store), so every drop path logs at warning with
        # enough context to find the misbehaving publisher.
        if len(parts) != 3:
            logger.warning(
                "dropping %d-part message from %s (want [topic, seq, "
                "payload])",
                len(parts),
                self.config.endpoint,
            )
            return None
        topic_raw, seq_raw, payload = parts
        try:
            topic = topic_raw.decode()
        except UnicodeDecodeError:
            logger.warning(
                "dropping message with undecodable topic from %s",
                self.config.endpoint,
            )
            return None
        parsed = parse_topic(topic)
        if parsed is None:
            logger.warning(
                "dropping message with malformed topic %r from %s",
                topic,
                self.config.endpoint,
            )
            return None
        pod_id, model = parsed

        seq = 0
        gap = 0
        if len(seq_raw) == 8:
            seq = struct.unpack(">Q", seq_raw)[0]
            last_seq = self._last_seq_by_topic.get(topic)
            if last_seq is not None and seq > last_seq + 1:
                gap = seq - last_seq - 1
                self.gap_count += gap
                METRICS.kvevents_seq_gaps.labels(pod=pod_id).inc(gap)
                logger.warning(
                    "sequence gap on %s: %d -> %d (%d events lost)",
                    topic,
                    last_seq,
                    seq,
                    gap,
                )
            self._last_seq_by_topic[topic] = seq

        trace(logger, "message topic=%s seq=%d", topic, seq)
        # seq_gap rides the message so a sampled ingestion trace can
        # surface the publisher-side loss alongside queue/apply timing.
        return Message(
            topic=topic,
            payload=payload,
            pod_identifier=pod_id,
            model_name=model,
            seq=seq,
            seq_gap=gap,
        )
