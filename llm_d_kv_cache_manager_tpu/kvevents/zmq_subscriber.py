"""KV-event wire format: topic parsing, seq tracking, message demux.

Wire format (reference: pkg/kvevents/zmq_subscriber.go:135-155, matching
vLLM's event publisher): 3-part messages ``[topic, seq, payload]`` where
``topic = "kv@<pod-id>@<model>"``, ``seq`` is a big-endian uint64, and
``payload`` is a msgpack ``EventBatch``.

This module owns the *demultiplexing* half of the event plane — shared
by the consolidated poller (``kvevents/poller.py``, the production
subscription path: a fixed pool of poller threads multiplexing many SUB
sockets) and by the legacy one-thread-per-pod :class:`ZMQSubscriber`
kept below as the bench baseline.

Sequence numbers are parsed per topic (``TopicSeqTracker``) and
classified three ways:

* ``seq == last + 1`` (or first sighting) — in order;
* ``seq > last + 1`` — a **gap**: ``seq - last - 1`` events were lost;
  counted in ``kvtpu_kvevents_seq_gaps_total{pod=...}`` and surfaced to
  an optional ``on_gap`` callback so the anti-entropy resync path
  (``kvevents/resync.py``) can mark the pod suspect instead of silently
  serving stale scores;
* ``seq < last`` — a **publisher restart** (the engine restarted and
  its counter reset to 1): the watermark resets to the new seq, the
  restart is counted in ``kvtpu_kvevents_publisher_restarts_total`` and
  it is NOT folded into the gap counter — a restarted counter would
  otherwise inflate gaps by ~``last`` on every engine restart.
  ``seq == last`` is a duplicate delivery: dropped from accounting
  entirely (watermark unchanged, no gap, no restart).
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import zmq

from llm_d_kv_cache_manager_tpu.kvevents.pool import Message
from llm_d_kv_cache_manager_tpu.metrics.collector import (
    METRICS,
    safe_label,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger, trace

logger = get_logger("kvevents.zmq")

TOPIC_PREFIX = "kv@"
POLL_INTERVAL_MS = 250
RECONNECT_BACKOFF_SECONDS = 5.0

# on_gap(pod_identifier, topic, events_lost) — called inline on the
# polling thread; implementations must be fast and non-blocking (the
# resync manager's mark_suspect only flips a set entry + notifies).
GapListener = Callable[[str, str, int], None]


def topic_filter_bytes(
    topic_filter: Optional[str], pod_identifier: str
) -> bytes:
    """The SUBSCRIBE prefix for one pod's channel: an explicit filter
    verbatim ("" = everything), else scoped to ``kv@<pod>@``."""
    if topic_filter is not None:
        return topic_filter.encode()
    return f"{TOPIC_PREFIX}{pod_identifier}@".encode()


def open_sub_socket(
    context: zmq.Context, endpoint: str, filter_: bytes, bind: bool
) -> zmq.Socket:
    """One pod's SUB socket, configured identically for the
    consolidated poller and the legacy per-pod subscriber (the bench
    A/Bs the two paths — their socket setup must never drift)."""
    sock = context.socket(zmq.SUB)
    sock.setsockopt(zmq.LINGER, 0)
    sock.setsockopt(zmq.SUBSCRIBE, filter_)
    if bind:
        sock.bind(endpoint)
    else:
        sock.connect(endpoint)
    return sock


def parse_topic(topic: str) -> Optional[tuple]:
    """``kv@<pod-id>@<model>`` -> (pod_id, model); None if malformed.

    Model names may themselves contain ``@`` (LoRA refs); split only twice.
    """
    if not topic.startswith(TOPIC_PREFIX):
        return None
    rest = topic[len(TOPIC_PREFIX):]
    pod_id, sep, model = rest.partition("@")
    if not sep or not pod_id or not model:
        return None
    return pod_id, model


@dataclass
class SeqObservation:
    """Classification of one (topic, seq) sighting."""

    gap: int = 0
    restarted: bool = False
    duplicate: bool = False


class TopicSeqTracker:
    """Per-topic sequence watermarks for one pod's event stream.

    NOT thread-safe by design: a tracker is owned by whichever single
    thread polls its pod's socket (one poller thread per socket in the
    consolidated pool; the dedicated thread in the legacy subscriber).
    Sequence numbers are independent per topic — model/LoRA streams
    from one pod each number from their own counter.
    """

    __slots__ = ("_last_seq_by_topic", "gap_count", "restart_count")

    def __init__(self) -> None:
        self._last_seq_by_topic: Dict[str, int] = {}
        self.gap_count = 0
        self.restart_count = 0

    def observe(self, topic: str, seq: int) -> SeqObservation:
        last = self._last_seq_by_topic.get(topic)
        if last is None or seq == last + 1:
            self._last_seq_by_topic[topic] = seq
            return SeqObservation()
        if seq > last + 1:
            gap = seq - last - 1
            self.gap_count += gap
            self._last_seq_by_topic[topic] = seq
            return SeqObservation(gap=gap)
        if seq == last:
            # Duplicate delivery (PUB fan-in quirk): not a restart, not
            # a gap — and the watermark must not move.
            return SeqObservation(duplicate=True)
        # seq < last: the publisher restarted and its counter reset.
        # Reset the watermark so the NEXT message is judged against the
        # new counter, and keep the gap metric honest.
        self.restart_count += 1
        self._last_seq_by_topic[topic] = seq
        return SeqObservation(restarted=True)


def parse_event_message(
    parts,
    endpoint: str,
    pod_identifier: str,
    tracker: Optional[TopicSeqTracker] = None,
    on_gap: Optional[GapListener] = None,
) -> Optional[Message]:
    """Decode one ``[topic, seq, payload]`` multipart into a Message.

    Shared by the consolidated poller and the legacy subscriber so both
    paths classify gaps/restarts identically.  Dropped frames are event
    loss (stale scores for that pod until re-store), so every drop path
    logs at warning with enough context to find the misbehaving
    publisher.  Returns None for malformed frames and duplicate seqs.

    ``payload`` may be any bytes-like object — the poller's zero-copy
    path passes a ``memoryview`` over the ZMQ frame, which rides the
    Message untouched into the (pre-)decode stage; topic and seq must
    be ``bytes`` (they are tiny and always copied out of the frame).
    """
    if len(parts) != 3:
        logger.warning(
            "dropping %d-part message from %s (want [topic, seq, payload])",
            len(parts),
            endpoint,
        )
        return None
    topic_raw, seq_raw, payload = parts
    try:
        topic = topic_raw.decode()
    except UnicodeDecodeError:
        logger.warning(
            "dropping message with undecodable topic from %s", endpoint
        )
        return None
    parsed = parse_topic(topic)
    if parsed is None:
        logger.warning(
            "dropping message with malformed topic %r from %s",
            topic,
            endpoint,
        )
        return None
    pod_id, model = parsed

    seq = 0
    gap = 0
    if len(seq_raw) == 8:
        seq = struct.unpack(">Q", seq_raw)[0]
        if tracker is not None:
            observed = tracker.observe(topic, seq)
            if observed.duplicate:
                trace(logger, "duplicate seq %d on %s; dropping", seq, topic)
                return None
            if observed.restarted:
                METRICS.kvevents_publisher_restarts.labels(
                    pod=safe_label(pod_id)
                ).inc()
                logger.info(
                    "publisher restart on %s: counter reset to %d "
                    "(watermark reset, not counted as a gap)",
                    topic,
                    seq,
                )
            elif observed.gap:
                gap = observed.gap
                METRICS.kvevents_seq_gaps.labels(pod=safe_label(pod_id)).inc(gap)
                logger.warning(
                    "sequence gap on %s: -> %d (%d events lost)",
                    topic,
                    seq,
                    gap,
                )
                if on_gap is not None:
                    try:
                        on_gap(pod_id, topic, gap)
                    except Exception:  # noqa: BLE001 — listener bugs
                        logger.exception(
                            "gap listener failed for pod %s", pod_id
                        )

    trace(logger, "message topic=%s seq=%d", topic, seq)
    # seq_gap rides the message so a sampled ingestion trace can
    # surface the publisher-side loss alongside queue/apply timing.
    return Message(
        topic=topic,
        payload=payload,
        pod_identifier=pod_id,
        model_name=model,
        seq=seq,
        seq_gap=gap,
    )


@dataclass
class ZMQSubscriberConfig:
    endpoint: str
    pod_identifier: str
    # Subscribe to this pod's topics only; "" subscribes to everything.
    topic_filter: Optional[str] = None
    # bind=True for local test endpoints, connect for remote pods
    # (reference: zmq_subscriber.go:92-105).
    bind: bool = False

    def filter_bytes(self) -> bytes:
        return topic_filter_bytes(self.topic_filter, self.pod_identifier)


class ZMQSubscriber:
    """LEGACY one SUB socket + dedicated polling thread per pod.

    Superseded by the consolidated poller pool (``kvevents/poller.py``)
    which multiplexes many pods' sockets onto a fixed thread pool —
    thread count and idle wakeups scale with ``KVEVENTS_POLLERS``, not
    fleet size.  This class is retained as the thread-per-pod baseline
    for the ``event_storm`` bench regime (the A/B the consolidation is
    measured against) and for single-socket tools; production paths go
    through :class:`~.subscriber_manager.SubscriberManager`, which no
    longer uses it.
    """

    def __init__(
        self,
        config: ZMQSubscriberConfig,
        sink: Callable[[Message], None],
        context: Optional[zmq.Context] = None,
        on_gap: Optional[GapListener] = None,
    ) -> None:
        self.config = config
        self._sink = sink
        self._context = context or zmq.Context.instance()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._on_gap = on_gap
        self.tracker = TopicSeqTracker()

    @property
    def gap_count(self) -> int:
        return self.tracker.gap_count

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run,
            name=f"kvtpu-zmq-{self.config.pod_identifier}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _open_socket(self) -> zmq.Socket:
        return open_sub_socket(
            self._context,
            self.config.endpoint,
            self.config.filter_bytes(),
            self.config.bind,
        )

    def _run(self) -> None:
        while not self._stop.is_set():
            sock = None
            try:
                sock = self._open_socket()
                self._poll_loop(sock)
            except Exception as exc:  # noqa: BLE001 — thread must survive
                logger.warning(
                    "subscriber for %s errored (%s); reconnecting in %.0fs",
                    self.config.pod_identifier,
                    exc,
                    RECONNECT_BACKOFF_SECONDS,
                )
                self._stop.wait(RECONNECT_BACKOFF_SECONDS)
            finally:
                if sock is not None:
                    sock.close()

    def _poll_loop(self, sock: zmq.Socket) -> None:
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)
        while not self._stop.is_set():
            if not dict(poller.poll(POLL_INTERVAL_MS)):
                continue
            parts = sock.recv_multipart()
            message = self._parse_message(parts)
            if message is None:
                continue
            try:
                self._sink(message)
            except Exception:  # noqa: BLE001 — sink bugs must not kill us
                logger.exception(
                    "sink failed for a message from %s; dropping it",
                    self.config.pod_identifier,
                )

    def _parse_message(self, parts) -> Optional[Message]:
        return parse_event_message(
            parts,
            endpoint=self.config.endpoint,
            pod_identifier=self.config.pod_identifier,
            tracker=self.tracker,
            on_gap=self._on_gap,
        )


__all__ = [
    "GapListener",
    "POLL_INTERVAL_MS",
    "RECONNECT_BACKOFF_SECONDS",
    "SeqObservation",
    "TOPIC_PREFIX",
    "TopicSeqTracker",
    "ZMQSubscriber",
    "ZMQSubscriberConfig",
    "open_sub_socket",
    "parse_event_message",
    "parse_topic",
    "topic_filter_bytes",
]
