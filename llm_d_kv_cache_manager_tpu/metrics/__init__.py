from llm_d_kv_cache_manager_tpu.metrics.collector import (  # noqa: F401
    METRICS,
    KVCacheMetrics,
    start_metrics_logging,
)
