"""Prometheus metrics for the indexer stack.

Capability parity with pkg/kvcache/metrics/collector.go: index
admissions/evictions/lookup counters, lookup-latency histogram, per-lookup
max-pod-hit counter, tokenization latency/token counters labeled by backend,
and a periodic "metrics beat" logger.  Exposed through a dedicated registry
so embedding applications can mount ``/metrics`` wherever they like.
"""

from __future__ import annotations

import gc
import os
import threading
import time
from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("metrics")

_NAMESPACE = "kvtpu"


class KVCacheMetrics:
    def __init__(self, registry: Optional[CollectorRegistry] = None) -> None:
        self.registry = registry or CollectorRegistry()
        self.index_admissions = Counter(
            f"{_NAMESPACE}_kvcache_index_admissions_total",
            "Number of KV-block keys admitted into the index.",
            registry=self.registry,
        )
        self.index_evictions = Counter(
            f"{_NAMESPACE}_kvcache_index_evictions_total",
            "Number of KV-block eviction operations applied to the index.",
            registry=self.registry,
        )
        self.index_lookup_requests = Counter(
            f"{_NAMESPACE}_kvcache_index_lookup_requests_total",
            "Number of index lookups served.",
            registry=self.registry,
        )
        self.index_lookup_hits = Counter(
            f"{_NAMESPACE}_kvcache_index_lookup_hits_total",
            "Number of index lookups that returned at least one pod.",
            registry=self.registry,
        )
        self.index_max_pod_hits = Counter(
            f"{_NAMESPACE}_kvcache_index_max_pod_hit_count_total",
            "Sum over lookups of the max per-pod hit count.",
            registry=self.registry,
        )
        self.index_lookup_latency = Histogram(
            f"{_NAMESPACE}_kvcache_index_lookup_latency_seconds",
            "Latency of index lookups.",
            registry=self.registry,
            buckets=(
                0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
            ),
        )
        self.tokenization_latency = Histogram(
            f"{_NAMESPACE}_tokenization_latency_seconds",
            "Latency of tokenization calls by backend.",
            ("tokenizer",),
            registry=self.registry,
            # Sub-millisecond resolution: the prefix-store fast path and
            # local fast tokenizers finish far below the Prometheus
            # default 5ms first bucket (same style as
            # index_lookup_latency above).
            buckets=(
                0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
            ),
        )
        self.tokenization_tokens = Counter(
            f"{_NAMESPACE}_tokenization_tokens_total",
            "Tokens produced by tokenization calls by backend.",
            ("tokenizer",),
            registry=self.registry,
        )
        self.tokenization_prefix_fast_path = Counter(
            f"{_NAMESPACE}_tokenization_prefix_fast_path_total",
            "Tokenizations served from the prefix store (coverage >= "
            "min_prefix_overlap_ratio) instead of a full tokenizer run.",
            registry=self.registry,
        )
        self.kvevents_dropped = Counter(
            f"{_NAMESPACE}_kvevents_dropped_total",
            "KV-event messages dropped by the ingestion pool by reason.",
            ("reason",),
            registry=self.registry,
        )
        self.kvevents_batch_size = Histogram(
            f"{_NAMESPACE}_kvevents_batch_size",
            "Messages drained per kvevents worker wake-up (the batched "
            "apply path; 1 = no batching headroom, the queue never "
            "backed up).",
            registry=self.registry,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128),
        )
        self.kvevents_seq_gaps = Counter(
            f"{_NAMESPACE}_kvevents_seq_gaps_total",
            "Events lost to publisher sequence-number gaps, by pod.",
            ("pod",),
            registry=self.registry,
        )
        self.kvevents_publisher_restarts = Counter(
            f"{_NAMESPACE}_kvevents_publisher_restarts_total",
            "Publisher restarts detected as per-topic sequence-number "
            "regressions (counter reset); distinguished from gaps so an "
            "engine restart does not inflate the loss signal.",
            ("pod",),
            registry=self.registry,
        )
        self.kvevents_pod_shed = Counter(
            f"{_NAMESPACE}_kvevents_pod_shed_total",
            "Event messages shed by per-pod flow control, by the pod "
            "whose message was dropped (docs/event-plane.md).",
            ("pod",),
            registry=self.registry,
        )
        self.kvevents_pod_backlog = Gauge(
            f"{_NAMESPACE}_kvevents_pod_backlog",
            "Queued (not yet applied) event messages per pod in the "
            "ingestion pool's shard lanes.",
            ("pod",),
            registry=self.registry,
        )
        self.kvevents_poller_sockets = Gauge(
            f"{_NAMESPACE}_kvevents_poller_sockets",
            "SUB sockets currently multiplexed by each consolidated "
            "event-plane poller thread.",
            ("poller",),
            registry=self.registry,
        )
        self.kvevents_suspect_pods = Gauge(
            f"{_NAMESPACE}_kvevents_suspect_pods",
            "Pods whose index entries are suspect (sequence gap "
            "detected, resync not yet completed).",
            registry=self.registry,
        )
        self.kvevents_resyncs = Counter(
            f"{_NAMESPACE}_kvevents_resyncs_total",
            "Anti-entropy pod resyncs by outcome.",
            ("outcome",),
            registry=self.registry,
        )
        self.kvevents_resync_staleness = Histogram(
            f"{_NAMESPACE}_kvevents_resync_staleness_seconds",
            "Index-staleness window per resynced pod: first detected "
            "gap to repair (purge + inventory re-apply) completed.",
            registry=self.registry,
            buckets=(
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0,
            ),
        )
        self.persistence_journal_records = Counter(
            f"{_NAMESPACE}_persistence_journal_records_total",
            "Index operations appended to the persistence journal by op.",
            ("op",),
            registry=self.registry,
        )
        self.persistence_journal_lag = Gauge(
            f"{_NAMESPACE}_persistence_journal_records_since_snapshot",
            "Journal records appended since the last published snapshot "
            "(replay cost of a crash right now).",
            registry=self.registry,
        )
        self.persistence_snapshot_timestamp = Gauge(
            f"{_NAMESPACE}_persistence_snapshot_created_timestamp_seconds",
            "Unix time of the last published index snapshot.",
            registry=self.registry,
        )
        self.persistence_snapshot_bytes = Gauge(
            f"{_NAMESPACE}_persistence_snapshot_bytes",
            "Size of the last published index snapshot.",
            registry=self.registry,
        )
        self.persistence_replayed_records = Counter(
            f"{_NAMESPACE}_persistence_replayed_records_total",
            "Journal records replayed into the index during recovery.",
            registry=self.registry,
        )
        self.persistence_recoveries = Counter(
            f"{_NAMESPACE}_persistence_recoveries_total",
            "Startup recoveries by outcome (warm: state restored; cold: "
            "nothing on disk).",
            ("outcome",),
            registry=self.registry,
        )
        self.offload_bytes = Counter(
            f"{_NAMESPACE}_offload_bytes_total",
            "Bytes moved by the offload engine by direction.",
            ("direction",),
            registry=self.registry,
        )
        self.offload_jobs = Counter(
            f"{_NAMESPACE}_offload_jobs_total",
            "Offload jobs completed by direction and status.",
            ("direction", "status"),
            registry=self.registry,
        )
        self.offload_staging_lane_waits = Counter(
            f"{_NAMESPACE}_offload_staging_lane_waits_total",
            "Staged transfers that had to wait for a free per-chip "
            "staging lane (lane-saturation backpressure; climbing "
            "value = raise OFFLOAD_STAGING_LANES or the engine is "
            "wedged).",
            registry=self.registry,
        )
        # Cache-efficiency analytics (analytics/ledger.py): per-request
        # hit attribution on the scoring read path.  At
        # CACHESTATS_SAMPLE_RATE < 1 these are an unbiased sample of
        # the request mix, not a total count (same caveat as
        # stage_latency below).
        self.cachestats_requests = Counter(
            f"{_NAMESPACE}_cachestats_requests_total",
            "Scored requests recorded by the hit-attribution ledger, by "
            "outcome (hit: best pod covered >= hit_ratio of the prompt's "
            "block chain; partial: anything matched; miss: nothing).",
            ("outcome",),
            registry=self.registry,
        )
        self.cachestats_tier_hits = Counter(
            f"{_NAMESPACE}_cachestats_tier_hits_total",
            "Scored blocks attributed to each memory tier (the best "
            "resident tier per consecutive matched block).",
            ("tier",),
            registry=self.registry,
        )
        self.cachestats_reuse_distance = Histogram(
            f"{_NAMESPACE}_cachestats_reuse_distance",
            "Distinct scored requests between re-encounters of a prefix "
            "family (working-set reuse distance).",
            registry=self.registry,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096,
                     16384),
        )
        self.cachestats_families = Gauge(
            f"{_NAMESPACE}_cachestats_families",
            "Prefix families currently tracked, summed across ledger "
            "instances (each LRU-bounded by CACHESTATS_MAX_FAMILIES); "
            "maintained by deltas so several ledgers aggregate.",
            registry=self.registry,
        )
        # Index-truth audit plane (analytics/auditor.py).
        self.index_divergence_ratio = Gauge(
            f"{_NAMESPACE}_index_divergence_ratio",
            "Per-pod index-vs-inventory divergence from the last audit: "
            "(phantom + missing + wrong-tier blocks) / union size.",
            ("pod",),
            registry=self.registry,
        )
        self.index_audits = Counter(
            f"{_NAMESPACE}_index_audits_total",
            "Pod audits by outcome (clean / divergent / failed).",
            ("outcome",),
            registry=self.registry,
        )
        self.index_audit_blocks = Counter(
            f"{_NAMESPACE}_index_audit_blocks_total",
            "Divergent blocks found by audits, by kind (phantom / "
            "missing / wrong_tier).",
            ("kind",),
            registry=self.registry,
        )
        # Predictive tiering (tiering/; docs/tiering.md).
        self.tiering_demotions = Counter(
            f"{_NAMESPACE}_tiering_demotions_total",
            "Proactive block-group demotions by transition "
            "(hbm_to_host / host_to_storage).",
            ("transition",),
            registry=self.registry,
        )
        self.tiering_demotion_bytes = Counter(
            f"{_NAMESPACE}_tiering_demotion_bytes_total",
            "Bytes moved down the memory ladder by proactive demotion, "
            "by transition.",
            ("transition",),
            registry=self.registry,
        )
        self.tiering_advice = Counter(
            f"{_NAMESPACE}_tiering_advice_total",
            "Compute-or-load advisor decisions by action "
            "(load / recompute / hybrid).",
            ("action",),
            registry=self.registry,
        )
        self.tiering_evictions = Counter(
            f"{_NAMESPACE}_tiering_policy_evictions_total",
            "Eviction victims chosen by the predictive policy, by "
            "backend and mode (predicted: a reuse prediction ranked the "
            "sample; fallback_lru: no prediction known, LRU-proxy order).",
            ("backend", "mode"),
            registry=self.registry,
        )
        self.tiering_readback_rtt = Gauge(
            f"{_NAMESPACE}_tiering_readback_rtt_seconds",
            "EWMA of observed offload load-job latency (submit to "
            "harvest) feeding the compute-or-load advisor.",
            registry=self.registry,
        )
        self.tiering_writeback_rtt = Gauge(
            f"{_NAMESPACE}_tiering_writeback_rtt_seconds",
            "EWMA of observed offload store-job latency (submit to "
            "harvest) feeding the advisor's write-side cost model.",
            registry=self.registry,
        )
        self.tiering_snapshot_age = Gauge(
            f"{_NAMESPACE}_tiering_snapshot_age_seconds",
            "Age of the policy feed's current prediction snapshot.",
            registry=self.registry,
        )
        # KV-transfer planning plane (transfer/; docs/transfer.md).
        self.transfer_plans = Counter(
            f"{_NAMESPACE}_transfer_plans_total",
            "Transfer-planner decisions by outcome (planned / warmup / "
            "holder-not-overloaded / no-holder / no-target / "
            "too-few-blocks / no-block-bytes / no-rtt-observations / "
            "recompute-cheaper / pod-invalidated / expired).",
            ("outcome",),
            registry=self.registry,
        )
        self.transfer_executions = Counter(
            f"{_NAMESPACE}_transfer_executions_total",
            "Executed transfer plans by outcome (copied / moved / "
            "partial-copied / partial-moved / invalidated / stale).",
            ("outcome",),
            registry=self.registry,
        )
        self.transfer_bytes = Counter(
            f"{_NAMESPACE}_transfer_bytes_total",
            "Bytes moved pod-to-pod by executed transfer plans.",
            registry=self.registry,
        )
        self.transfer_warmup_moves = Counter(
            f"{_NAMESPACE}_transfer_warmup_moves_total",
            "Hot-family pre-placements executed by the warm-up worker.",
            registry=self.registry,
        )
        self.transfer_cold_pods = Gauge(
            f"{_NAMESPACE}_transfer_cold_pods",
            "Pods registered cold with warm-up transfers still pending.",
            registry=self.registry,
        )
        # Replicated index service (cluster/; docs/replication.md).
        self.cluster_ring_version = Gauge(
            f"{_NAMESPACE}_cluster_ring_version",
            "Version of the router's consistent-hash ring (bumps on "
            "every membership change).",
            registry=self.registry,
        )
        self.cluster_replicas_alive = Gauge(
            f"{_NAMESPACE}_cluster_replicas_alive",
            "Replicas currently considered alive by the router's "
            "membership (heartbeat-healthy).",
            registry=self.registry,
        )
        self.cluster_failovers = Counter(
            f"{_NAMESPACE}_cluster_failovers_total",
            "Replicas removed from the ring (heartbeat timeout or "
            "observed transport failure); each removal re-routes the "
            "replica's slice to its rendezvous runner-up.",
            registry=self.registry,
        )
        self.cluster_rpc_latency = Histogram(
            f"{_NAMESPACE}_cluster_rpc_latency_seconds",
            "Latency of router->replica RPCs by replica method (the "
            "fan-out attribution view; per-replica panels live in "
            "/debug/cluster).",
            ("method",),
            registry=self.registry,
            buckets=(
                0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
            ),
        )
        self.cluster_rpc_errors = Counter(
            f"{_NAMESPACE}_cluster_rpc_errors_total",
            "Router->replica RPC transport failures by replica and "
            "failure kind (timeout / refused / wire_decode / "
            "http_status / killed / io); each marks the replica dead "
            "and retries on the failover owner.",
            ("replica", "kind"),
            registry=self.registry,
        )
        self.cluster_replica_lag = Gauge(
            f"{_NAMESPACE}_cluster_replica_lag_records",
            "Journal records a replication follower was behind its "
            "primary when its last sync poll began, by followed peer.",
            ("peer",),
            registry=self.registry,
        )
        self.cluster_replication_applied = Counter(
            f"{_NAMESPACE}_cluster_replication_applied_total",
            "Journal records applied by replication followers, by "
            "followed peer.",
            ("peer",),
            registry=self.registry,
        )
        # Read-path SLO feed: end-to-end scored-request latency at the
        # service boundary (api/http_service.py), unsampled — unlike
        # stage_latency below this sees EVERY request, so the SLO
        # engine's latency SLI (obs/slo.py) windows an unbiased stream.
        self.score_latency = Histogram(
            f"{_NAMESPACE}_score_latency_seconds",
            "End-to-end latency of scored requests at the HTTP service "
            "boundary (every request — errors included, not just "
            "sampled traces).",
            registry=self.registry,
            buckets=(
                0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5,
            ),
        )
        self.score_requests = Counter(
            f"{_NAMESPACE}_score_requests_total",
            "Scored requests at the HTTP service boundary by outcome "
            "(ok / error) — the availability SLI's feed: a fully "
            "failing service must read as violated, not as a no-data "
            "latency SLI.",
            ("outcome",),
            registry=self.registry,
        )
        # Score memo visibility (kvcache/indexer.py): 1 when the
        # exact-prompt memo was requested but self-disabled because the
        # backend lacks version_vector/touch_chain.  The in-memory
        # backend AND the cluster RemoteIndex (version-vectored since
        # the pipelined read path; docs/replication.md) both support
        # the memo, so a 1 here means a custom backend without the
        # optimistic-validation surface.
        self.score_memo_disabled = Gauge(
            f"{_NAMESPACE}_score_memo_disabled",
            "1 when the request score memo is configured but disabled "
            "by an index backend lacking version_vector/touch_chain, "
            "else 0 (the in-memory backend and the cluster RemoteIndex "
            "both support it).",
            registry=self.registry,
        )
        # SLO engine (obs/slo.py; docs/observability.md).
        self.slo_state = Gauge(
            f"{_NAMESPACE}_slo_state",
            "Degradation-envelope state per SLI (0 healthy / 1 "
            "degraded / 2 violated); sli=\"overall\" is the worst.",
            ("sli",),
            registry=self.registry,
        )
        self.slo_burn_rate = Gauge(
            f"{_NAMESPACE}_slo_burn_rate",
            "Error-budget burn rate per SLI and evaluation window "
            "(1.0 = burning exactly the objective's budget).",
            ("sli", "window"),
            registry=self.registry,
        )
        # Lock-contention telemetry (utils/lockorder.py timing mode;
        # docs/observability.md "Lock contention").  Only contended
        # sampled acquires land here — with LOCK_CONTENTION_SAMPLE
        # unset/0 both families stay empty.
        self.lock_wait = Histogram(
            f"{_NAMESPACE}_lock_wait_seconds",
            "Wait time of contended sampled acquires per tracked lock "
            "name (LOCK_CONTENTION_SAMPLE gates the probe rate).",
            ("lock",),
            registry=self.registry,
            buckets=(
                0.000005, 0.00001, 0.000025, 0.00005, 0.0001, 0.00025,
                0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 1.0,
            ),
        )
        self.lock_contention = Counter(
            f"{_NAMESPACE}_lock_contention_total",
            "Contended sampled acquires per tracked lock name (the "
            "non-blocking probe failed; the acquire had to wait).",
            ("lock",),
            registry=self.registry,
        )
        # Process runtime gauges (refreshed by update_process_metrics:
        # the metrics beat and the gauge timeline both call it).
        self.process_rss = Gauge(
            f"{_NAMESPACE}_process_rss_bytes",
            "Resident set size of this process (/proc/self/statm).",
            registry=self.registry,
        )
        self.process_open_fds = Gauge(
            f"{_NAMESPACE}_process_open_fds",
            "Open file descriptors of this process (/proc/self/fd).",
            registry=self.registry,
        )
        self.process_threads = Gauge(
            f"{_NAMESPACE}_process_threads",
            "Live Python threads (threading.active_count()).",
            registry=self.registry,
        )
        self.gc_collections = Counter(
            f"{_NAMESPACE}_gc_collections_total",
            "Garbage-collection passes by generation (gc callbacks; "
            "install_gc_metrics()).",
            ("gen",),
            registry=self.registry,
        )
        self.gc_pause = Histogram(
            f"{_NAMESPACE}_gc_pause_seconds",
            "Wall time of each garbage-collection pass (the collecting "
            "thread is stalled for the duration; every other thread "
            "contends for the GIL against it).",
            registry=self.registry,
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 1.0,
            ),
        )
        # Incident capture plane (obs/capture.py; docs/observability.md
        # "Incident capture & replay").
        self.build_info = Gauge(
            f"{_NAMESPACE}_build_info",
            "Always 1; labels carry the package version and the "
            "config fingerprint (hash of the resolved score-relevant "
            "env knobs) stamped into every capture header and "
            "incident bundle — replays refuse mismatched artifacts.",
            ("version", "fingerprint"),
            registry=self.registry,
        )
        self.capture_ring_bytes = Gauge(
            f"{_NAMESPACE}_capture_ring_bytes",
            "Estimated bytes retained by the input flight recorder "
            "per ingress source (kvevents / scores); bounded by "
            "CAPTURE_MAX_BYTES.",
            ("source",),
            registry=self.registry,
        )
        self.capture_records = Counter(
            f"{_NAMESPACE}_capture_records_total",
            "Ingress records appended to the input flight recorder "
            "per source (refreshed in batches off the hot path).",
            ("source",),
            registry=self.registry,
        )
        self.incident_bundles = Counter(
            f"{_NAMESPACE}_incident_bundles_total",
            "Incident bundles written by outcome (ok / failed); "
            "SLO-triggered and /admin/incident both count.",
            ("outcome",),
            registry=self.registry,
        )
        # What-if engine (obs/whatif.py; docs/observability.md
        # "What-if engine").
        self.whatif_runs = Counter(
            f"{_NAMESPACE}_whatif_runs_total",
            "What-if replays completed, by kind (run / ab) and "
            "outcome; CLI, /admin/whatif, and perf-trend gate runs "
            "all count.",
            ("kind", "outcome"),
            registry=self.registry,
        )
        self.whatif_events = Counter(
            f"{_NAMESPACE}_whatif_events_total",
            "Recorded kvevents offered to what-if candidate stacks, "
            "by the candidate's flow-control disposition (admitted / "
            "shed).",
            ("disposition",),
            registry=self.registry,
        )
        self.whatif_hit_rate = Gauge(
            f"{_NAMESPACE}_whatif_hit_rate",
            "Hit rate measured by the most recent what-if replay, per "
            "arm name (fraction of replayed scores with a non-zero "
            "best score).",
            ("arm",),
            registry=self.registry,
        )
        # Per-stage latencies fed by the tracing subsystem (obs/trace.py):
        # every span of a sampled trace lands here under its span name, so
        # the aggregate view and the per-request flight-recorder view
        # share one stage vocabulary ("tokenize", "index_lookup",
        # "kvevents.apply", "offload.io", ...).  Only sampled requests
        # contribute — at low TRACE_SAMPLE_RATE this is an unbiased
        # sample of the stage mix, not a total count.
        self.stage_latency = Histogram(
            f"{_NAMESPACE}_stage_latency_seconds",
            "Per-stage latency of traced requests, by pipeline stage.",
            ("stage",),
            registry=self.registry,
            buckets=(
                0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
            ),
        )

    def exposition(self) -> bytes:
        return generate_latest(self.registry)


# Process-wide default instance; modules import this rather than plumbing a
# registry through every constructor.
METRICS = KVCacheMetrics()

# Label values longer than this are truncated (with a marker) before
# reaching the registry: label values are unbounded wire input in the
# pod-labeled families, and a single hostile topic string must not blow
# up every scrape.
MAX_LABEL_LEN = 120


def safe_label(value: str) -> str:
    """Bound and sanitize a wire-sourced label value.

    The exposition format itself escapes ``\\``, ``\"`` and newlines
    (prometheus_client does this on output; pinned by
    tests/test_metrics_endpoint.py) — this helper handles what escaping
    cannot: unbounded length and non-printable control characters in
    values that arrive from the network (pod identifiers parsed out of
    ZMQ topics).  Printable text passes through unchanged, so normal
    pod names keep their exact label identity.
    """
    text = str(value)
    if any(ch < " " or ch == "\x7f" for ch in text):
        text = "".join(
            ch if ch >= " " and ch != "\x7f" else "�" for ch in text
        )
    if len(text) > MAX_LABEL_LEN:
        text = text[: MAX_LABEL_LEN - 1] + "…"
    return text


def counter_total(counter: Counter) -> float:
    """Sum of a counter's ``_total`` samples across all label sets.

    ``collect()[0].samples[0]`` only works for unlabeled counters — a
    labeled counter's first sample is whichever label set was created
    first (and with no children yet there are NO samples at all).
    Summing by the ``_total`` suffix handles unlabeled, labeled, and
    empty counters alike and skips ``_created`` gauge samples.
    """
    total = 0.0
    for metric in counter.collect():
        for sample in metric.samples:
            if sample.name.endswith("_total"):
                total += sample.value
    return total


def gauge_value(gauge: Gauge) -> float:
    """Current value of an unlabeled gauge (0.0 when never set)."""
    for metric in gauge.collect():
        for sample in metric.samples:
            return sample.value
    return 0.0


def gauge_total(gauge: Gauge) -> float:
    """Sum of a labeled gauge's samples across all label sets (e.g.
    total event backlog over the per-pod ``kvevents_pod_backlog``
    series); 0.0 with no children yet."""
    total = 0.0
    for metric in gauge.collect():
        for sample in metric.samples:
            total += sample.value
    return total


# ------------------------ process runtime metrics ------------------------

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def update_process_metrics() -> dict:
    """Refresh the process runtime gauges and return their values.

    Called by the metrics beat and by the gauge timeline's sampler
    (obs/timeline.py) — cheap by construction: two /proc reads and a
    thread count, no allocation-heavy walks.  Platforms without /proc
    (macOS dev boxes) report what they can and leave the rest at 0.
    """
    out = {"rss_bytes": 0.0, "open_fds": 0.0, "threads": 0.0}
    try:
        with open("/proc/self/statm", "rb") as statm:
            out["rss_bytes"] = float(
                int(statm.read().split()[1]) * _PAGE_SIZE
            )
    except (OSError, ValueError, IndexError):
        pass  # kvlint: disable=KV005 — no /proc: gauge stays 0
    try:
        out["open_fds"] = float(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass  # kvlint: disable=KV005 — no /proc: gauge stays 0
    out["threads"] = float(threading.active_count())
    METRICS.process_rss.set(out["rss_bytes"])
    METRICS.process_open_fds.set(out["open_fds"])
    METRICS.process_threads.set(out["threads"])
    return out


# gc callbacks run on whichever thread triggered the collection, and
# CPython serializes collections — a per-generation start stamp keyed
# by generation is race-free without a lock.
_gc_starts: dict = {}
_gc_installed = False
_gc_children: dict = {}


def _gc_callback(phase: str, info: dict) -> None:
    gen = info.get("generation", 0)
    if phase == "start":
        _gc_starts[gen] = time.perf_counter()
        return
    start = _gc_starts.pop(gen, None)
    child = _gc_children.get(gen)
    if child is None:
        child = METRICS.gc_collections.labels(gen=str(gen))
        _gc_children[gen] = child
    child.inc()
    if start is not None:
        METRICS.gc_pause.observe(time.perf_counter() - start)


def install_gc_metrics() -> bool:
    """Hook ``gc.callbacks`` so every collection pass lands in
    ``kvtpu_gc_collections_total{gen}`` / ``kvtpu_gc_pause_seconds``.
    Idempotent; returns True when (already) installed."""
    global _gc_installed
    if _gc_installed:
        return True
    gc.callbacks.append(_gc_callback)
    _gc_installed = True
    return True


def uninstall_gc_metrics() -> None:
    """Remove the gc hook (test isolation)."""
    global _gc_installed
    if _gc_installed:
        try:
            gc.callbacks.remove(_gc_callback)
        except ValueError:
            logger.warning("gc metrics callback already removed")
        _gc_installed = False


def start_metrics_logging(interval_seconds: float = 60.0) -> threading.Event:
    """Log a periodic one-line metrics beat; returns a stop event."""
    stop = threading.Event()

    def beat() -> None:
        while not stop.wait(interval_seconds):
            # dropped_events and journal_lag earn their place on the
            # line during incidents: a climbing drop count means event
            # shards are shedding (stale index), a climbing lag means a
            # crash right now replays that many journal records.  The
            # process block (rss/fds/threads/gc) is the leak telltale:
            # those climb for minutes before anything else degrades.
            proc = update_process_metrics()
            # capture_kb / incidents join the line for the same reason
            # dropped_events did: during an incident the flight
            # recorder's occupancy says whether the replay window is
            # still intact, and a climbing incident count says the SLO
            # engine is actively bundling (docs/observability.md).
            logger.info(
                "metrics beat: admissions=%d evictions=%d lookups=%d "
                "hits=%d dropped_events=%d journal_lag=%d rss_mb=%.1f "
                "fds=%d threads=%d gc=%d capture_kb=%.0f incidents=%d",
                counter_total(METRICS.index_admissions),
                counter_total(METRICS.index_evictions),
                counter_total(METRICS.index_lookup_requests),
                counter_total(METRICS.index_lookup_hits),
                counter_total(METRICS.kvevents_dropped),
                gauge_value(METRICS.persistence_journal_lag),
                proc["rss_bytes"] / 1e6,
                proc["open_fds"],
                proc["threads"],
                counter_total(METRICS.gc_collections),
                gauge_total(METRICS.capture_ring_bytes) / 1e3,
                counter_total(METRICS.incident_bundles),
            )

    thread = threading.Thread(target=beat, name="kvtpu-metrics-beat", daemon=True)
    thread.start()
    return stop
