from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import (  # noqa: F401
    KVCachePool,
    KVCachePoolConfig,
)
