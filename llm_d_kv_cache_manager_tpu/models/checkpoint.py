"""Model checkpoint/resume over orbax.

The reference's index is deliberately ephemeral (rebuild from the event
stream; SURVEY §5) and its durable artifacts are the offloaded KV files
— both carried over here.  What the TPU stack adds on top is model
state: train steps (models/llama.py, models/moe.py) need durable
params/optimizer snapshots.  Orbax handles sharded arrays natively, so
a restore onto a different mesh layout works by passing the target
shardings via ``abstract_target``.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(path: str, state: Any, force: bool = True) -> str:
    """Persist a pytree (params / (params, opt_state) / anything jax).

    ``path`` must be absolute (orbax requirement); returns it.
    """
    path = os.path.abspath(path)
    checkpointer = _checkpointer()
    checkpointer.save(path, state, force=force)
    checkpointer.wait_until_finished()
    return path


def restore_checkpoint(path: str, abstract_target: Optional[Any] = None):
    """Restore a pytree saved by ``save_checkpoint``.

    ``abstract_target`` (e.g. ``jax.eval_shape`` of the state, with
    ``jax.sharding.NamedSharding`` leaves) restores each array directly
    onto its target device layout — the multi-chip resume path.  With
    None, arrays land as numpy on host.
    """
    import warnings

    checkpointer = _checkpointer()
    with warnings.catch_warnings():
        # Restoring without explicit shardings (host restore, or an
        # abstract target built for structure only) makes orbax read the
        # layouts from the checkpoint's own sharding file and warn about
        # it.  That is this function's documented contract, not a
        # misuse; keep the warning out of every caller's output.
        warnings.filterwarnings(
            "ignore",
            message="Sharding info not provided when restoring",
            category=UserWarning,
        )
        if abstract_target is not None:
            return checkpointer.restore(
                os.path.abspath(path), target=abstract_target
            )
        return checkpointer.restore(os.path.abspath(path))


def abstract_like(state: Any, shardings: Optional[Any] = None):
    """Build the ``abstract_target`` for ``restore_checkpoint``:
    ShapeDtypeStructs of ``state``, carrying ``shardings`` if given."""
    abstract = jax.eval_shape(lambda x: x, state)
    if shardings is None:
        return abstract
    return jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract,
        shardings,
    )
