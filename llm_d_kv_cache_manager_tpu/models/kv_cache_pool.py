"""Paged KV-cache pool: the TPU-resident block store the connector pages.

One stacked array ``[num_layers, num_blocks, 2(K/V), block_size,
num_kv_heads, head_dim]`` rather than per-layer tensors: a single jitted
gather/scatter moves a block batch across *all* layers in one XLA op and
one DMA, where the reference's CUDA path loops cudaMemcpyAsync per
block x layer (tensor_copier.cu:50-97).  The layer axis also gives
pipeline-parallel sharding a natural home (shard axis 0 over the ``pp``
mesh axis; blocks axis stays replicated within a stage).

Sharded pools: pass a NamedSharding; gather/scatter then run under the
same sharding and XLA inserts the collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class KVCachePoolConfig:
    num_layers: int
    num_blocks: int
    block_size: int
    num_kv_heads: int
    head_dim: int
    dtype: str = "bfloat16"


@jax.jit
def _gather(kv: jax.Array, block_ids: jax.Array) -> jax.Array:
    return jnp.take(kv, block_ids, axis=1)


@jax.jit
def _scatter(kv: jax.Array, block_ids: jax.Array, blocks: jax.Array):
    return kv.at[:, block_ids].set(blocks)


# Donation variant used when the pool owns its array exclusively.
_scatter_donated = jax.jit(
    lambda kv, ids, blocks: kv.at[:, ids].set(blocks), donate_argnums=(0,)
)


@jax.jit
def _gather_block_major(kv: jax.Array, block_ids: jax.Array) -> jax.Array:
    """Gather + layer-major -> block-major transpose ON DEVICE: the
    staging engine's file layout is ``[n, L, 2, bs, h, d]``, and doing
    the moveaxis in XLA means the host-bound DMA already carries file
    bytes (no host-side ``np.ascontiguousarray`` re-layout copy)."""
    return jnp.moveaxis(jnp.take(kv, block_ids, axis=1), 1, 0)


def supports_pinned_host(device: Optional[jax.Device] = None) -> bool:
    """Whether the backend exposes a pinned_host memory space (TPU yes,
    CPU tests typically yes on recent jaxlib, but never assumed)."""
    try:
        device = device or jax.devices()[0]
        return any(
            memory.kind == "pinned_host"
            for memory in device.addressable_memories()
        )
    except Exception:
        return False


class KVCachePool:
    def __init__(
        self,
        config: KVCachePoolConfig,
        sharding: Optional[jax.sharding.Sharding] = None,
    ) -> None:
        self.config = config
        shape = (
            config.num_layers,
            config.num_blocks,
            2,
            config.block_size,
            config.num_kv_heads,
            config.head_dim,
        )
        dtype = jnp.dtype(config.dtype)
        if sharding is not None:
            self.kv = jax.device_put(jnp.zeros(shape, dtype), sharding)
        else:
            self.kv = jnp.zeros(shape, dtype)
        self._pinned_host = supports_pinned_host(
            next(iter(self.kv.devices()))
        )

    @property
    def pinned_host(self) -> bool:
        """Whether this pool's device exposes a pinned_host memory
        space (the staging engine's fast-path gate; flips off after a
        failed transfer so the probe is never retried per job)."""
        return self._pinned_host

    @property
    def block_nbytes(self) -> int:
        """Bytes of one block across all layers (the offload unit)."""
        c = self.config
        return (
            c.num_layers
            * 2
            * c.block_size
            * c.num_kv_heads
            * c.head_dim
            * jnp.dtype(c.dtype).itemsize
        )

    def gather_to_host(self, block_ids: Sequence[int]) -> np.ndarray:
        """Pull blocks to host: one gather in HBM + one transfer.

        Uses the pinned_host memory space when the backend has one (TPU:
        DMA straight into pinned pages, the staging role CUDA pinned
        buffers play in the reference).  Returns
        ``[num_layers, n, 2, block_size, heads, dim]``.
        """
        ids = jnp.asarray(np.asarray(block_ids, dtype=np.int32))
        gathered = _gather(self.kv, ids)
        if self._pinned_host:
            try:
                gathered = jax.device_put(
                    gathered, jax.memory.TransferToMemoryKind("pinned_host")
                )
            except Exception:
                self._pinned_host = False
        return np.asarray(jax.device_get(gathered))

    def stage_gather_pinned(self, block_ids: Sequence[int]) -> jax.Array:
        """Device gather+transpose, then an ASYNC DMA into pinned_host.

        Returns the pinned ``[n, L, 2, bs, h, d]`` array without
        forcing it, so the caller can overlap this slot's DMA with the
        previous slot's file I/O (the staging engine's double-buffered
        pipeline) and force only at submit time.  Raises when the
        backend has no pinned_host space — callers gate on
        :attr:`pinned_host` and fall back to :meth:`gather_block_major`.
        """
        if not self._pinned_host:
            raise RuntimeError("device exposes no pinned_host memory space")
        ids = jnp.asarray(np.asarray(block_ids, dtype=np.int32))
        gathered = _gather_block_major(self.kv, ids)
        return jax.device_put(
            gathered, jax.memory.TransferToMemoryKind("pinned_host")
        )

    def gather_block_major(self, block_ids: Sequence[int]) -> np.ndarray:
        """Block-major host gather ``[n, L, 2, bs, h, d]`` — the file
        byte layout, transposed on device (one copy fewer than
        :meth:`gather_to_host` + host moveaxis).  Pinned DMA when the
        backend supports it, plain transfer otherwise."""
        ids = jnp.asarray(np.asarray(block_ids, dtype=np.int32))
        gathered = _gather_block_major(self.kv, ids)
        if self._pinned_host:
            try:
                gathered = jax.device_put(
                    gathered, jax.memory.TransferToMemoryKind("pinned_host")
                )
            except Exception:
                self._pinned_host = False
        return np.asarray(jax.device_get(gathered))

    def scatter_block_major(
        self, block_ids: Sequence[int], group: np.ndarray
    ) -> None:
        """Scatter a block-major ``[n, L, 2, bs, h, d]`` host group (the
        staging engine's slot/file layout) into the pool."""
        self.scatter_from_host(block_ids, np.moveaxis(group, 0, 1))

    def scatter_from_host(
        self,
        block_ids: Sequence[int],
        blocks: np.ndarray,
        donate: bool = False,
    ) -> None:
        """Upload a host block batch and scatter it into the pool.

        ``donate=True`` lets XLA reuse the old pool buffer (halves peak
        HBM) but deletes it — only safe when no external reference to
        ``self.kv`` exists (the serving loop holds one between steps,
        so the connector's async load path must keep the default).
        """
        ids = jnp.asarray(np.asarray(block_ids, dtype=np.int32))
        uploaded = jnp.asarray(blocks, dtype=self.kv.dtype)
        scatter = _scatter_donated if donate else _scatter
        self.kv = scatter(self.kv, ids, uploaded)

    def write_block(self, block_id: int, block: np.ndarray) -> None:
        """Test/demo helper: set one block's contents."""
        self.scatter_from_host([block_id], block[:, None])
