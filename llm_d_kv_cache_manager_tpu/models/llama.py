"""Flagship model: Llama-family decoder, TPU-first.

The serving fleet in BASELINE.json runs Llama-3-8B on v5e; this module
is that model family in idiomatic JAX — pure-function params pytree,
``lax.scan`` over a stacked layer axis (one compiled layer body,
compiler-friendly control flow), bf16 matmuls with f32 softmax/norm
accumulation for the MXU, and PartitionSpecs over the canonical mesh
axes (parallel/mesh.py):

- params: layer axis over ``pp``, heads/ffn-hidden over ``tp``
- activations: batch over ``dp``, sequence over ``sp``
- serving KV state: the paged pool (models/kv_cache_pool.py), written
  by prefill and read by ``paged_attention`` at decode — the compute
  counterpart of the KV-block index the manager tracks fleet-wide.

Capabilities: dense forward (training / scoring), paged prefill +
decode (serving), ring-attention prefill for long context (ops/
ring_attention.py), and a full train step (optax AdamW) used by the
multi-chip dry run.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from llm_d_kv_cache_manager_tpu.ops.attention import causal_gqa_attention
from llm_d_kv_cache_manager_tpu.ops.flash_attention import flash_gqa_attention
from llm_d_kv_cache_manager_tpu.ops import flash_pallas
from llm_d_kv_cache_manager_tpu.ops.paged_decode_pallas import (
    paged_decode_attention_pallas,
)
from llm_d_kv_cache_manager_tpu.ops.paged_attention import paged_attention
from llm_d_kv_cache_manager_tpu.ops.ring_attention import (
    ring_for_mesh,
    stripe,
    unstripe,
)

Params = Dict[str, Any]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1408
    rope_theta: float = 500000.0
    block_size: int = 16  # paged-KV block, matches the index block size
    dtype: str = "bfloat16"
    # Key-axis length at/above which prefill attention switches from the
    # dense path to blockwise flash attention (O(tile) memory; the
    # long-context prefill path).  Static shapes make this a trace-time
    # choice.
    flash_attention_min_len: int = 1024
    # Decode attention over the paged pool.  "auto" resolves to the
    # XLA gather EVERYWHERE — the recorded routing decision: the last
    # committed chip measurement put the Pallas kernel at 1.09x over
    # the gather (within noise; r4), and the routing rule requires
    # >= 1.3x at two serving shapes before Pallas may be the default
    # (bench.py DECODE_ROUTE_MIN_SPEEDUP).  bench.py re-measures every
    # run and sets "pallas" explicitly when the kernel earns it;
    # "pallas" / "gather" force one path.
    decode_attention: str = "auto"
    # Pool blocks the Pallas decode kernel fetches per grid step;
    # bench.py detail.kernels sweeps this at serving shapes and routes
    # the measured winner here.
    decode_blocks_per_step: int = 4
    # Feed the decode-attention dots bf16 operands (f32 accumulation)
    # instead of upcasting K/V in VMEM; swept by bench.py alongside the
    # tile size.
    decode_mxu_native: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256,
            d_model=4096,
            n_layers=32,
            n_heads=32,
            n_kv_heads=8,
            d_ff=14336,
        )


def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    dtype = jnp.dtype(cfg.dtype)
    L, D, H, Hkv, Dh, F = (
        cfg.n_layers,
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
    )
    keys = jax.random.split(rng, 8)

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in**-0.5).astype(
            dtype
        )

    return {
        "embed": norm_init(keys[0], (cfg.vocab_size, D), D),
        "layers": {
            "ln1": jnp.ones((L, D), dtype),
            "ln2": jnp.ones((L, D), dtype),
            "wq": norm_init(keys[1], (L, D, H, Dh), D),
            "wk": norm_init(keys[2], (L, D, Hkv, Dh), D),
            "wv": norm_init(keys[3], (L, D, Hkv, Dh), D),
            "wo": norm_init(keys[4], (L, H, Dh, D), H * Dh),
            "w_gate": norm_init(keys[5], (L, D, F), D),
            "w_up": norm_init(keys[6], (L, D, F), D),
            "w_down": norm_init(keys[7], (L, F, D), F),
        },
        "ln_f": jnp.ones((D,), dtype),
    }


def param_pspecs(cfg: LlamaConfig) -> Params:
    """PartitionSpec pytree matching init_params (axes: parallel/mesh)."""
    return {
        "embed": P(None, "tp"),
        "layers": {
            "ln1": P("pp", None),
            "ln2": P("pp", None),
            "wq": P("pp", None, "tp", None),
            "wk": P("pp", None, "tp", None),
            "wv": P("pp", None, "tp", None),
            "wo": P("pp", "tp", None, None),
            "w_gate": P("pp", None, "tp"),
            "w_up": P("pp", None, "tp"),
            "w_down": P("pp", "tp", None),
        },
        "ln_f": P(None),
    }


def _rms_norm(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    norm = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (norm * w.astype(jnp.float32)).astype(x.dtype)


def _rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """x: [B, T, H, D] (D even); positions: [B, T]."""
    D = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    return jnp.concatenate(
        (x1 * cos - x2 * sin, x2 * cos + x1 * sin), axis=-1
    ).astype(x.dtype)


def _mlp(x: jnp.ndarray, lp: Params) -> jnp.ndarray:
    gate = jnp.einsum("btd,df->btf", x, lp["w_gate"])
    up = jnp.einsum("btd,df->btf", x, lp["w_up"])
    hidden = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    return jnp.einsum("btf,fd->btd", hidden, lp["w_down"])


def _qkv(x: jnp.ndarray, lp: Params, positions: jnp.ndarray, theta: float):
    q = jnp.einsum("btd,dhk->bthk", x, lp["wq"])
    k = jnp.einsum("btd,dhk->bthk", x, lp["wk"])
    v = jnp.einsum("btd,dhk->bthk", x, lp["wv"])
    return _rope(q, positions, theta), _rope(k, positions, theta), v


def _logits(x: jnp.ndarray, params: Params) -> jnp.ndarray:
    """Shared epilogue: final norm + tied-embedding head, f32 logits for
    a stable softmax/loss."""
    x = _rms_norm(x, params["ln_f"])
    return jnp.einsum(
        "...d,vd->...v",
        x.astype(jnp.float32),
        params["embed"].astype(jnp.float32),
    )


def _prefill_attention(q, k, v, cfg: LlamaConfig, q_offset=0, use_flash=True):
    """Dense for short sequences, blockwise flash for long (static
    shapes make the switch a trace-time decision).

    Flash routing on TPU: wide q tiles (full/paged prefill) go to the
    Pallas kernel (ops/flash_pallas.py, ~2x the scan op's throughput on
    8k prefill); short continuation suffixes keep the scan op, whose
    cost is dominated by the K/V read either way.  ``use_flash=False``
    forces dense: neither flash op has a custom VJP, so under ``grad``
    they keep the same O(Tq*Tk) residuals as dense while serializing
    the backward chunk-by-chunk — training paths should differentiate
    through the fused dense einsum instead.
    """
    if use_flash and k.shape[1] >= cfg.flash_attention_min_len:
        if (
            q.shape[1] >= cfg.flash_attention_min_len
            and isinstance(q_offset, int)
            and jax.default_backend() == "tpu"
            and flash_pallas.fits_vmem(
                k.shape[1], k.shape[-1], jnp.dtype(k.dtype).itemsize
            )
        ):
            # Beyond the VMEM budget the scan op streams K/V from HBM
            # at any length (e.g. 32k+ prompts).
            return flash_pallas.flash_gqa_attention_pallas(
                q, k, v, q_offset=q_offset
            )
        return flash_gqa_attention(q, k, v, q_offset=q_offset)
    return causal_gqa_attention(q, k, v, q_offset=q_offset)


def forward(
    params: Params,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    positions: Optional[jnp.ndarray] = None,
    use_flash: bool = True,
    sp_mesh=None,
    ring_striped: bool = False,
    ring_impl: str = "auto",
    ring_interpret: bool = False,
) -> jnp.ndarray:
    """Dense forward: tokens [B, T] -> logits [B, T, V].

    ``sp_mesh``: a Mesh with an ``sp`` axis routes attention through
    ring attention (ops/ring_attention.py) — the long-context prefill
    path: activations stay sequence-sharded over ``sp``, K/V chunks
    rotate over ICI, and only attention crosses devices.  Inference
    path (no custom VJP; train through the dense/flash route).  The
    ring's causal mask derives from each chunk's ring position, i.e.
    global positions 0..T-1 — custom ``positions`` are rejected rather
    than silently mismasked.

    ``ring_striped``: run the whole network in the striped (token-
    interleaved) sequence layout — tokens AND positions are striped at
    entry, every layer computes in stripe order (norms/MLP/logits are
    position-independent; RoPE gets the striped physical positions),
    attention runs the balanced striped ring, and the logits are
    unstriped at exit, so the returned contract is unchanged.
    ``ring_impl`` defaults to ``"auto"`` (the flash body on TPU, the
    portable einsum body elsewhere); ``"flash"`` forces the mask-aware
    Pallas partial that skips masked sub-tiles — with ``ring_striped``
    it halves per-step MXU work (ops/ring_flash_pallas.py).
    """
    B, T = tokens.shape
    if sp_mesh is not None and positions is not None:
        raise ValueError(
            "sp_mesh ring attention assumes default positions 0..T-1 "
            "(its causal mask is derived from ring chunk indices); "
            "custom positions would be RoPE-rotated but mis-masked"
        )
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    ring = None
    striped = False
    if sp_mesh is not None:
        striped = ring_striped and sp_mesh.shape["sp"] > 1
        if striped:
            ring_size = sp_mesh.shape["sp"]
            tokens = stripe(tokens, ring_size)
            # Positions stay PHYSICAL (RoPE rotates by true token
            # index); only their order is striped to match the tokens.
            positions = stripe(positions, ring_size)
        ring = ring_for_mesh(
            sp_mesh,
            striped=striped,
            impl=ring_impl,
            interpret=ring_interpret,
        )
    x = jnp.take(params["embed"], tokens, axis=0)

    def layer(x, lp):
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _qkv(h, lp, positions, cfg.rope_theta)
        if ring is not None:
            attn = ring(q, k, v)
        else:
            attn = _prefill_attention(q, k, v, cfg, use_flash=use_flash)
        x = x + jnp.einsum("bthk,hkd->btd", attn, lp["wo"])
        x = x + _mlp(_rms_norm(x, lp["ln2"]), lp)
        return x, None

    x, _ = lax.scan(layer, x, params["layers"])
    if striped:
        x = unstripe(x, sp_mesh.shape["sp"])
    return _logits(x, params)


def _scatter_kv_blocks(kv_layer, k, v, block_ids, block_size):
    """Write per-token K/V ([B, T, Hkv, Dh] each, T a multiple of
    ``block_size``) into the pool blocks named by ``block_ids``
    ([B, T/block_size]).  ONE layout for every prefill path — were it
    duplicated, a pool layout change could silently diverge between
    them."""
    B, T = k.shape[:2]
    kv = jnp.stack((k, v), axis=2)  # [B, T, 2, Hkv, Dh]
    kv = kv.reshape(
        B, T // block_size, block_size, 2, kv.shape[-2], kv.shape[-1]
    ).transpose(0, 1, 3, 2, 4, 5)  # [B, nb, 2, block, Hkv, Dh]
    return kv_layer.at[block_ids.reshape(-1)].set(
        kv.reshape((-1,) + kv.shape[2:]).astype(kv_layer.dtype)
    )


def prefill_paged(
    params: Params,
    tokens: jnp.ndarray,
    kv_pool: jnp.ndarray,
    block_table: jnp.ndarray,
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Prefill writing per-layer K/V into the paged pool.

    tokens: [B, T] with T % block_size == 0 (pad; padding blocks may be
    overwritten — give padded sequences scratch block ids).
    kv_pool: [L, num_blocks, 2, block_size, Hkv, Dh] (KVCachePool.kv).
    block_table: [B, T/block_size] pool block ids for each sequence.
    Returns (logits [B, T, V], new kv_pool).
    """
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    x = jnp.take(params["embed"], tokens, axis=0)

    def layer(x, inputs):
        lp, kv_layer = inputs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _qkv(h, lp, positions, cfg.rope_theta)
        attn = _prefill_attention(q, k, v, cfg)
        x = x + jnp.einsum("bthk,hkd->btd", attn, lp["wo"])
        x = x + _mlp(_rms_norm(x, lp["ln2"]), lp)
        kv_layer = _scatter_kv_blocks(
            kv_layer, k, v, block_table, cfg.block_size
        )
        return x, kv_layer

    x, kv_pool = lax.scan(layer, x, (params["layers"], kv_pool))
    return _logits(x, params), kv_pool


def prefill_continue(
    params: Params,
    tokens: jnp.ndarray,
    kv_pool: jnp.ndarray,
    block_table: jnp.ndarray,
    prefix_len: int,
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Prefill only the uncached suffix of a prompt (prefix-cache hit).

    The first ``prefix_len`` tokens' K/V already live in the pool (a
    prior request stored them, or the offload connector loaded them);
    this computes the suffix in one dense pass attending over
    gathered-prefix + new K/V, and scatters the suffix blocks back.
    This is what turns an index hit into real TTFT savings — the
    compute analogue of vLLM's prefix-cache hit that the reference
    routes toward (SURVEY.md §6 north star).

    tokens: [B, Ts] suffix tokens, Ts % block_size == 0.
    block_table: [B, (prefix_len + Ts) / block_size] — prefix blocks
    first, then the blocks to write.  ``prefix_len`` is static
    (% block_size == 0); one compile per distinct padded prefix length.
    Returns (suffix logits [B, Ts, V], new kv_pool).
    """
    B, Ts = tokens.shape
    if prefix_len % cfg.block_size or Ts % cfg.block_size:
        raise ValueError("prefix_len and Ts must be block_size multiples")
    npre = prefix_len // cfg.block_size
    nsuf = Ts // cfg.block_size
    positions = jnp.broadcast_to(
        prefix_len + jnp.arange(Ts), (B, Ts)
    )
    x = jnp.take(params["embed"], tokens, axis=0)
    prefix_ids = block_table[:, :npre]  # [B, npre]
    suffix_ids = block_table[:, npre : npre + nsuf]

    def layer(x, inputs):
        lp, kv_layer = inputs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _qkv(h, lp, positions, cfg.rope_theta)
        # Gather the prefix K/V: [B, npre, 2, block, Hkv, Dh].
        pre = jnp.take(kv_layer, prefix_ids, axis=0)
        pre = pre.transpose(0, 2, 1, 3, 4, 5).reshape(
            B, 2, prefix_len, k.shape[-2], k.shape[-1]
        )
        k_full = jnp.concatenate(
            (pre[:, 0].astype(k.dtype), k), axis=1
        )  # [B, prefix+Ts, Hkv, Dh]
        v_full = jnp.concatenate((pre[:, 1].astype(v.dtype), v), axis=1)
        attn = _prefill_attention(q, k_full, v_full, cfg, q_offset=prefix_len)
        x = x + jnp.einsum("bthk,hkd->btd", attn, lp["wo"])
        x = x + _mlp(_rms_norm(x, lp["ln2"]), lp)
        kv_layer = _scatter_kv_blocks(
            kv_layer, k, v, suffix_ids, cfg.block_size
        )
        return x, kv_layer

    x, kv_pool = lax.scan(layer, x, (params["layers"], kv_pool))
    return _logits(x, params), kv_pool


def prefill_chunked(
    params: Params,
    tokens: jnp.ndarray,
    kv_pool: jnp.ndarray,
    block_table: jnp.ndarray,
    cfg: LlamaConfig,
    chunk_tokens: int = 2048,
    seq_len: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Bounded-memory long-prompt prefill (vLLM's chunked prefill in
    the paged-pool design): the prompt is processed in fixed-size
    chunks, each writing its K/V blocks into the pool FIRST and then
    attending over everything written so far through the blockwise
    flash op — whose dynamic ``q_offset`` makes this ONE compiled
    chunk step regardless of prompt length, with runtime-skipped
    masked chunks.  Network activations are O(chunk) instead of O(T);
    the per-layer K/V gather still materializes the O(T) context
    (like prefill_continue's prefix gather) — what this bounds is the
    activation side, not the KV read.

    tokens: [B, T] with T % chunk_tokens == 0 and chunk_tokens %
    block_size == 0; block_table: [B, T / block_size].  ``seq_len``
    ([B], defaults to T everywhere): each sequence's TRUE length —
    prompts are padded up to a chunk multiple, and the returned
    logits are taken at position ``seq_len-1``, never at a pad
    position (pad tokens still run and write scratch blocks, but
    causality keeps them invisible to real positions).
    Returns (true-last-position logits [B, V], new kv_pool) — the
    serving contract (the next sampled token); intermediate
    positions' logits are not materialized.
    """
    B, T = tokens.shape
    C = chunk_tokens
    if T % C or C % cfg.block_size:
        raise ValueError(
            "chunk_tokens must divide T, and block_size must divide "
            f"chunk_tokens (T={T}, chunk={C}, block={cfg.block_size})"
        )
    n_chunks = T // C
    blocks_per_chunk = C // cfg.block_size
    Hkv, Dh = cfg.n_kv_heads, cfg.head_dim
    if seq_len is None:
        seq_len = jnp.full((B,), T, jnp.int32)
    # Clamped into range: an out-of-range length (caller forgot the
    # pad, off-by-one) must select a real position — otherwise no
    # chunk ever matches and the serving logits would silently come
    # from the zero-initialized carry.
    last_pos = jnp.clip(seq_len - 1, 0, T - 1)  # [B]

    def chunk_step(carry, i):
        kv_pool, last_h = carry
        start = i * C
        tok = lax.dynamic_slice_in_dim(tokens, start, C, axis=1)
        positions = jnp.broadcast_to(jnp.arange(C), (B, C)) + start
        x = jnp.take(params["embed"], tok, axis=0)
        chunk_ids = lax.dynamic_slice_in_dim(
            block_table, i * blocks_per_chunk, blocks_per_chunk, axis=1
        )

        def layer(x, inputs):
            lp, kv_layer = inputs
            h = _rms_norm(x, lp["ln1"])
            q, k, v = _qkv(h, lp, positions, cfg.rope_theta)
            # Scatter this chunk's K/V first: its keys then live in
            # the pool like every earlier chunk's, and ONE gathered
            # read serves the whole causal context.
            kv_layer = _scatter_kv_blocks(
                kv_layer, k, v, chunk_ids, cfg.block_size
            )
            full = jnp.take(kv_layer, block_table, axis=0)
            # [B, nb, 2, bs, Hkv, Dh] -> [B, T, Hkv, Dh] per half.
            k_full = full[:, :, 0].reshape(B, T, Hkv, Dh).astype(
                k.dtype
            )
            v_full = full[:, :, 1].reshape(B, T, Hkv, Dh).astype(
                v.dtype
            )
            # Causal mask with the chunk's dynamic offset hides every
            # pool position beyond the chunk's last token, including
            # blocks not written yet.
            attn = flash_gqa_attention(
                q, k_full, v_full, q_offset=start
            )
            x = x + jnp.einsum("bthk,hkd->btd", attn, lp["wo"])
            x = x + _mlp(_rms_norm(x, lp["ln2"]), lp)
            return x, kv_layer

        x, kv_pool = lax.scan(layer, x, (params["layers"], kv_pool))
        # Pick each sequence's TRUE last hidden state when it falls in
        # this chunk (ragged lengths: pad positions must never produce
        # the serving logits).  Hidden state only — projecting every
        # chunk to [B, V] would run n_chunks vocab matmuls for
        # discarded outputs.
        in_chunk = last_pos // C == i  # [B]
        offset = jnp.clip(last_pos - start, 0, C - 1)
        picked = jnp.take_along_axis(
            x, offset[:, None, None].repeat(x.shape[-1], 2), axis=1
        )[:, 0]
        last_h = jnp.where(in_chunk[:, None], picked, last_h)
        return (kv_pool, last_h), None

    (kv_pool, last_h), _ = lax.scan(
        chunk_step,
        (kv_pool, jnp.zeros((B, cfg.d_model), jnp.dtype(cfg.dtype))),
        jnp.arange(n_chunks),
    )
    return _logits(last_h, params), kv_pool


def decode_step(
    params: Params,
    tokens: jnp.ndarray,
    kv_pool: jnp.ndarray,
    block_table: jnp.ndarray,
    context_len: jnp.ndarray,
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One decode step over the paged pool.

    tokens: [B] current token ids; context_len: [B] length *including*
    the current token; block_table: [B, max_blocks].  Writes the new
    token's K/V into the pool slot, attends over the table, and returns
    (logits [B, V], new kv_pool).
    """
    B = tokens.shape[0]
    pos = context_len - 1  # [B]
    x = jnp.take(params["embed"], tokens, axis=0)  # [B, D]
    block_idx = pos // cfg.block_size
    slot = pos % cfg.block_size
    block_ids = jnp.take_along_axis(
        block_table, block_idx[:, None], axis=1
    )[:, 0]

    def layer(x, inputs):
        lp, kv_layer = inputs
        h = _rms_norm(x, lp["ln1"])
        h3 = h[:, None]  # [B, 1, D]
        q, k, v = _qkv(h3, lp, pos[:, None], cfg.rope_theta)
        kv_new = jnp.stack((k[:, 0], v[:, 0]), axis=1)  # [B, 2, Hkv, Dh]
        kv_layer = kv_layer.at[block_ids, :, slot].set(
            kv_new.astype(kv_layer.dtype)
        )
        # "auto" = the recorded routing decision: the XLA gather (last
        # measured Pallas margin 1.09x — within noise — and the rule
        # requires >= 1.3x at two serving shapes; see LlamaConfig).
        # bench.py re-measures both compiled on the real chip every
        # run (detail.kernels) and sets "pallas" when it earns it.
        use_pallas = cfg.decode_attention == "pallas"
        if use_pallas:
            attn = paged_decode_attention_pallas(
                q[:, 0],
                kv_layer,
                block_table,
                context_len,
                blocks_per_step=cfg.decode_blocks_per_step,
                mxu_native=cfg.decode_mxu_native,
            )
        else:
            attn = paged_attention(
                q[:, 0], kv_layer, block_table, context_len
            )
        x = x + jnp.einsum("bhk,hkd->bd", attn, lp["wo"])
        h2 = _rms_norm(x, lp["ln2"])[:, None]
        x = x + _mlp(h2, lp)[:, 0]
        return x, kv_layer

    x, kv_pool = lax.scan(layer, x, (params["layers"], kv_pool))
    return _logits(x, params), kv_pool


# ---------------------------------------------------------------- training


def next_token_nll(
    logits: jnp.ndarray, tokens: jnp.ndarray
) -> jnp.ndarray:
    """Mean next-token cross entropy from full-length [B, T, V] logits.

    Shift-and-mask, not slice: ``tokens[:, :-1]`` inside jit makes an
    unevenly-sharded [B, T-1] intermediate when T is sharded over
    ``sp`` — XLA pads the short shard and the padded lanes' softmax
    backward emits NaN into the target-token embedding row (seen on
    sp x tp / sp x pp meshes).  Keeping every shape [B, T] and masking
    the final position avoids that; shared by the llama and MoE losses
    so the sharding-sensitive masking lives in one place.
    """
    B, T = tokens.shape
    targets = jnp.roll(tokens, -1, axis=1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = (jnp.arange(T) < T - 1).astype(nll.dtype)
    return (nll * mask).sum() / (B * (T - 1))


def loss_fn(
    params: Params, tokens: jnp.ndarray, cfg: LlamaConfig
) -> jnp.ndarray:
    """Next-token cross entropy over tokens [B, T] — identical to the
    sliced form (causality: logits for positions < T-1 cannot see token
    T-1), in the sharding-safe shape (see next_token_nll)."""
    logits = forward(params, tokens, cfg, use_flash=False)
    return next_token_nll(logits, tokens)


def make_optimizer(lr: float = 3e-4) -> optax.GradientTransformation:
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.1)


def train_step(
    params: Params,
    opt_state: Any,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
) -> Tuple[Params, Any, jnp.ndarray]:
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss
