"""Mixtral-style sparse-MoE decoder: the expert-parallel model family.

Same attention stack as the flagship dense model (models/llama.py —
GQA, RoPE, RMSNorm, bf16 on the MXU) with the MLP replaced by a top-k
routed expert layer in the GShard/Switch formulation that maps onto
TPUs: static expert capacity, one-hot dispatch/combine einsums (all
MXU contractions, no dynamic shapes), tokens over capacity dropped to
the residual path.  Experts shard over the mesh's ``ep`` axis
(parallel/mesh.py) — under pjit the dispatch einsum becomes the
all-to-all over ICI, which XLA inserts from the sharding constraints;
``tp`` additionally shards each expert's hidden dim.

The reference is a serving control plane with no model zoo; this
family exists for the TPU serving/benchmark stack (SURVEY.md §2.3:
fleet benchmarks ran Qwen3-32B and Llama — MoE covers the third major
architecture class) and to make the canonical mesh's ``ep`` axis real.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from llm_d_kv_cache_manager_tpu.models.llama import (
    _logits,
    _prefill_attention,
    _qkv,
    _rms_norm,
    next_token_nll,
)
from llm_d_kv_cache_manager_tpu.ops.ring_attention import (
    ring_for_mesh,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    d_model: int = 1024
    n_layers: int = 8
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 2816  # per-expert hidden dim
    n_experts: int = 8
    top_k: int = 2
    # Static per-expert slot budget: capacity = ceil(top_k * T / E) *
    # factor.  Overflowing tokens fall back to the residual stream.
    capacity_factor: float = 1.25
    rope_theta: float = 500000.0
    block_size: int = 16
    dtype: str = "bfloat16"
    flash_attention_min_len: int = 1024
    # Weight of the load-balancing auxiliary loss (Switch §2.2 form).
    router_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def capacity(self, tokens_per_batch: int) -> int:
        raw = self.top_k * tokens_per_batch / self.n_experts
        return max(int(math.ceil(raw * self.capacity_factor)), 1)


def init_params(rng: jax.Array, cfg: MoEConfig) -> Params:
    dtype = jnp.dtype(cfg.dtype)
    L, D, H, Hkv, Dh, F, E = (
        cfg.n_layers,
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_experts,
    )
    keys = jax.random.split(rng, 9)

    def norm_init(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) * fan_in**-0.5
        ).astype(dtype)

    return {
        "embed": norm_init(keys[0], (cfg.vocab_size, D), D),
        "layers": {
            "ln1": jnp.ones((L, D), dtype),
            "ln2": jnp.ones((L, D), dtype),
            "wq": norm_init(keys[1], (L, D, H, Dh), D),
            "wk": norm_init(keys[2], (L, D, Hkv, Dh), D),
            "wv": norm_init(keys[3], (L, D, Hkv, Dh), D),
            "wo": norm_init(keys[4], (L, H, Dh, D), H * Dh),
            # Router in f32: tiny, and logits precision decides routing.
            "router": jax.random.normal(keys[5], (L, D, E), jnp.float32)
            * D**-0.5,
            "w_gate": norm_init(keys[6], (L, E, D, F), D),
            "w_up": norm_init(keys[7], (L, E, D, F), D),
            "w_down": norm_init(keys[8], (L, E, F, D), F),
        },
        "ln_f": jnp.ones((D,), dtype),
    }


def param_pspecs(cfg: MoEConfig) -> Params:
    """PartitionSpec pytree (axes: parallel/mesh.py): experts over
    ``ep``, per-expert hidden over ``tp``, stacked layers over ``pp``."""
    return {
        "embed": P(None, "tp"),
        "layers": {
            "ln1": P("pp", None),
            "ln2": P("pp", None),
            "wq": P("pp", None, "tp", None),
            "wk": P("pp", None, "tp", None),
            "wv": P("pp", None, "tp", None),
            "wo": P("pp", "tp", None, None),
            "router": P("pp", None, None),
            "w_gate": P("pp", "ep", None, "tp"),
            "w_up": P("pp", "ep", None, "tp"),
            "w_down": P("pp", "ep", "tp", None),
        },
        "ln_f": P(None),
    }


def _route(
    x: jnp.ndarray, router: jnp.ndarray, cfg: MoEConfig
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k routing with static capacity.

    x: [S, D] flattened tokens.  Returns (dispatch [S, E, C] one-hot,
    combine [S, E, C] gate-weighted, aux load-balancing loss)."""
    S, _ = x.shape
    E, C = cfg.n_experts, cfg.capacity(S)

    logits = x.astype(jnp.float32) @ router  # [S, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # Top-k selection as k sequential argmax rounds (static shapes).
    remaining = probs
    dispatch = jnp.zeros((S, E, C), jnp.float32)
    combine = jnp.zeros((S, E, C), jnp.float32)
    # Slots already taken per expert, accumulated across rounds.
    fill = jnp.zeros((E,), jnp.int32)
    picked_gates = []
    picks = []
    for _ in range(cfg.top_k):
        choice = jnp.argmax(remaining, axis=-1)  # [S]
        gate = jnp.take_along_axis(
            probs, choice[:, None], axis=-1
        )[:, 0]
        picks.append(choice)
        picked_gates.append(gate)
        remaining = remaining * (
            1.0 - jax.nn.one_hot(choice, E, dtype=jnp.float32)
        )

    # Normalize the k gates per token (Mixtral renormalizes top-k).
    gate_sum = sum(picked_gates)
    for choice, gate in zip(picks, picked_gates):
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.float32)  # [S, E]
        # Position of each token within its chosen expert's queue:
        # tokens are served in sequence order (cumsum), plus slots the
        # earlier rounds already filled.
        position = (
            jnp.cumsum(onehot, axis=0) - 1.0 + fill[None, :].astype(
                jnp.float32
            )
        )  # [S, E]
        position_tok = jnp.sum(position * onehot, axis=-1)  # [S]
        keep = position_tok < C  # capacity drop
        slot = jax.nn.one_hot(
            jnp.where(keep, position_tok, C).astype(jnp.int32),
            C,
            dtype=jnp.float32,
        )  # [S, C] (dropped tokens one-hot nothing)
        contrib = onehot[:, :, None] * slot[:, None, :]  # [S, E, C]
        dispatch = dispatch + contrib * keep[:, None, None]
        combine = combine + contrib * (
            (gate / jnp.maximum(gate_sum, 1e-9)) * keep
        )[:, None, None]
        fill = fill + jnp.sum(
            onehot * keep[:, None], axis=0
        ).astype(jnp.int32)

    # Load-balancing aux loss: E * sum_e f_e * p_e (Switch/GShard).
    token_frac = jnp.mean(
        jax.nn.one_hot(picks[0], E, dtype=jnp.float32), axis=0
    )
    prob_frac = jnp.mean(probs, axis=0)
    aux = cfg.n_experts * jnp.sum(token_frac * prob_frac)
    return dispatch, combine, aux


def _mesh_in_context() -> bool:
    """Whether with_sharding_constraint can resolve a PartitionSpec:
    either a ``with mesh:`` context or a ``jax.set_mesh`` mesh."""
    from llm_d_kv_cache_manager_tpu.parallel.mesh import mesh_is_active

    return mesh_is_active()


def _constrain(x: jnp.ndarray, spec: P) -> jnp.ndarray:
    """Sharding constraint that is a deterministic no-op outside a mesh
    context (single-device tests and the unsharded serving path).

    The check is explicit rather than try/except: a swallowed
    RuntimeError would silently bake a constraint-free trace into the
    jit cache, and the expert all-to-all would never form."""
    if _mesh_in_context():
        return lax.with_sharding_constraint(x, spec)
    return x


def _moe_mlp(
    x: jnp.ndarray, lp: Params, cfg: MoEConfig
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Routed expert MLP.  x: [B, T, D] -> ([B, T, D], aux loss)."""
    B, T, D = x.shape
    flat = x.reshape(B * T, D)
    dispatch, combine, aux = _route(flat, lp["router"], cfg)
    dispatch = dispatch.astype(x.dtype)

    # [S, E, C] x [S, D] -> expert batches [E, C, D]: under ep sharding
    # this contraction IS the all-to-all (XLA SPMD inserts it).
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, flat)
    expert_in = _constrain(expert_in, P("ep", None, None))
    gate = jnp.einsum("ecd,edf->ecf", expert_in, lp["w_gate"])
    up = jnp.einsum("ecd,edf->ecf", expert_in, lp["w_up"])
    hidden = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    expert_out = jnp.einsum("ecf,efd->ecd", hidden, lp["w_down"])
    expert_out = _constrain(expert_out, P("ep", None, None))
    out = jnp.einsum(
        "sec,ecd->sd", combine.astype(x.dtype), expert_out
    )
    return out.reshape(B, T, D), aux


def forward(
    params: Params,
    tokens: jnp.ndarray,
    cfg: MoEConfig,
    use_flash: bool = True,
    sp_mesh=None,
    ring_impl: str = "auto",
    ring_interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense forward: tokens [B, T] -> (logits [B, T, V], aux loss).

    ``sp_mesh``: long-context prefill via ring attention over the
    ``sp`` axis, same wiring as the flagship model (llama.forward).
    CONTIGUOUS layout only: the striped layout reorders tokens, and
    MoE capacity routing is token-order-sensitive (drops are consumed
    in array order), so striping would silently change which tokens
    overflow — llama-only until striped-aware capacity ordering
    exists."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    x = jnp.take(params["embed"], tokens, axis=0)
    ring = None
    if sp_mesh is not None:
        ring = ring_for_mesh(
            sp_mesh, impl=ring_impl, interpret=ring_interpret
        )

    def layer(carry, lp):
        x, aux = carry
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _qkv(h, lp, positions, cfg.rope_theta)
        if ring is not None:
            attn = ring(q, k, v)
        else:
            attn = _prefill_attention(q, k, v, cfg, use_flash=use_flash)
        x = x + jnp.einsum("bthk,hkd->btd", attn, lp["wo"])
        moe_out, layer_aux = _moe_mlp(_rms_norm(x, lp["ln2"]), lp, cfg)
        return (x + moe_out, aux + layer_aux), None

    (x, aux), _ = lax.scan(layer, (x, jnp.float32(0.0)), params["layers"])
    return _logits(x, params), aux / cfg.n_layers


def loss_fn(
    params: Params, tokens: jnp.ndarray, cfg: MoEConfig
) -> jnp.ndarray:
    """Next-token cross entropy + router load-balancing loss.

    Shift-and-mask (llama.next_token_nll): slicing to [B, T-1] inside
    jit breaks even sequence sharding over ``sp`` (padded-lane softmax
    backward NaNs the target embedding row on combined meshes).

    NOT loss-curve-identical to the old sliced form: routing couples
    tokens (expert capacity is consumed in token order), so including
    position T-1 can change which earlier tokens are dropped under
    capacity pressure — and the aux balance statistic now covers all T
    positions.  A deliberate semantics change accepted with the
    sharding fix."""
    logits, aux = forward(params, tokens, cfg, use_flash=False)
    nll_mean = next_token_nll(logits, tokens)
    return nll_mean + cfg.router_aux_weight * aux


def make_optimizer(lr: float = 3e-4) -> optax.GradientTransformation:
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.1)


def train_step(
    params: Params,
    opt_state: Any,
    tokens: jnp.ndarray,
    cfg: MoEConfig,
    optimizer: optax.GradientTransformation,
) -> Tuple[Params, Any, jnp.ndarray]:
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss
