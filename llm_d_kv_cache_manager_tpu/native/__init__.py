"""Native runtime loader.

``get_library()`` returns the ctypes handle to libkvtpu_native.so, building
it on first use when a compiler is available; returns None otherwise so
every caller can fall back to pure Python.  Set ``KVTPU_DISABLE_NATIVE=1``
to force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.kvtpu_fnv1a64.restype = ctypes.c_uint64
    lib.kvtpu_fnv1a64.argtypes = [ctypes.c_char_p, ctypes.c_size_t]

    lib.kvtpu_hash_chain.restype = ctypes.c_size_t
    lib.kvtpu_hash_chain.argtypes = [
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint64),
    ]

    lib.kvtpu_engine_create.restype = ctypes.c_void_p
    lib.kvtpu_engine_create.argtypes = [ctypes.c_size_t, ctypes.c_int]
    lib.kvtpu_engine_destroy.argtypes = [ctypes.c_void_p]

    lib.kvtpu_engine_store.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_size_t,
        ctypes.c_int,
    ]
    lib.kvtpu_engine_load.argtypes = lib.kvtpu_engine_store.argtypes[:-1]
    lib.kvtpu_engine_get_finished.restype = ctypes.c_size_t
    lib.kvtpu_engine_get_finished.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_size_t,
    ]
    lib.kvtpu_engine_wait.restype = ctypes.c_int32
    lib.kvtpu_engine_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.kvtpu_file_exists.restype = ctypes.c_int
    lib.kvtpu_file_exists.argtypes = [ctypes.c_char_p]
    return lib


def get_library() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if os.environ.get("KVTPU_DISABLE_NATIVE"):
        return None
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        try:
            from llm_d_kv_cache_manager_tpu.native.build import build

            path = build()
            if path is None:
                return None
            _lib = _configure(ctypes.CDLL(path))
        except (OSError, RuntimeError) as exc:
            from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

            get_logger("native").warning(
                "native library unavailable (%s); using the slower "
                "pure-Python fallback",
                exc,
            )
            _lib = None
        return _lib
