"""Builds libkvtpu_native.so with g++ (no CUDA, no external deps).

Usage: ``python -m llm_d_kv_cache_manager_tpu.native.build [--force]``.
The library lands next to this file and is picked up by the ctypes loader;
callers that find no compiler fall back to pure Python transparently.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile

_SRC_FILES = ["hashing.cpp", "numa.cpp", "thread_pool.cpp", "file_io.cpp", "engine.cpp"]

LIB_NAME = "libkvtpu_native.so"


def _paths():
    here = os.path.dirname(os.path.abspath(__file__))
    src_dir = os.path.join(here, "src")
    return here, src_dir, os.path.join(here, LIB_NAME)


def lib_path() -> str:
    return _paths()[2]


def needs_build() -> bool:
    here, src_dir, lib = _paths()
    if not os.path.exists(lib):
        return True
    lib_mtime = os.path.getmtime(lib)
    sources = [os.path.join(src_dir, f) for f in _SRC_FILES]
    sources.append(os.path.join(src_dir, "kvtpu_native.hpp"))
    return any(os.path.getmtime(s) > lib_mtime for s in sources)


def build(force: bool = False) -> str | None:
    """Compile the library; returns its path, or None if no compiler."""
    here, src_dir, lib = _paths()
    if not force and not needs_build():
        return lib
    compiler = shutil.which("g++") or shutil.which("c++")
    if compiler is None:
        return None
    sources = [os.path.join(src_dir, f) for f in _SRC_FILES]
    # Build into a temp file then rename: concurrent builders (e.g.
    # parallel test workers) must never load a torn .so.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=here)
    os.close(fd)
    cmd = [
        compiler, "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        "-Wall", "-Wextra", "-o", tmp, *sources,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, lib)
    except subprocess.CalledProcessError as exc:
        os.unlink(tmp)
        raise RuntimeError(
            f"native build failed:\n{exc.stderr}"
        ) from exc
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return lib


def build_stress(tsan: bool = False) -> str | None:
    """Compile the TSan stress harness (src/stress_main.cpp); returns
    the binary path, or None if no compiler.  With ``tsan=True`` the
    whole engine is instrumented with ThreadSanitizer — the race
    detection SURVEY.md §5 notes the reference never wired up."""
    here, src_dir, _ = _paths()
    compiler = shutil.which("g++") or shutil.which("c++")
    if compiler is None:
        return None
    out = os.path.join(here, "stress_tsan" if tsan else "stress")
    sources = [os.path.join(src_dir, f) for f in _SRC_FILES]
    sources.append(os.path.join(src_dir, "stress_main.cpp"))
    cmd = [compiler, "-std=c++17", "-pthread", "-Wall", "-Wextra"]
    if tsan:
        cmd += ["-fsanitize=thread", "-O1", "-g"]
    else:
        cmd += ["-O2"]
    # Temp-then-rename like build(): concurrent builders (parallel test
    # workers) must never exec a torn or ETXTBSY-blocked binary.
    fd, tmp = tempfile.mkstemp(prefix="stress.", dir=here)
    os.close(fd)
    cmd += ["-o", tmp, *sources]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.chmod(tmp, 0o755)
        os.replace(tmp, out)
    except subprocess.CalledProcessError as exc:
        os.unlink(tmp)
        raise RuntimeError(f"stress build failed:\n{exc.stderr}") from exc
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return out


if __name__ == "__main__":
    if "--stress" in sys.argv or "--stress-tsan" in sys.argv:
        binary = build_stress(tsan="--stress-tsan" in sys.argv)
        if binary is None:
            print("no C++ compiler found")
            sys.exit(1)
        print(f"built {binary}; running")
        # Scratch dir cleaned up after the run (repeated `make
        # native-race` must not accumulate ~26 MB per run in /tmp).
        with tempfile.TemporaryDirectory(
            prefix="kvtpu-stress-"
        ) as scratch:
            env = dict(
                os.environ,
                TSAN_OPTIONS="halt_on_error=1",
                KVTPU_STRESS_DIR=scratch,
            )
            sys.exit(subprocess.run([binary], env=env).returncode)
    result = build(force="--force" in sys.argv)
    if result is None:
        print("no C++ compiler found; pure-Python fallback will be used")
        sys.exit(1)
    print(f"built {result}")
