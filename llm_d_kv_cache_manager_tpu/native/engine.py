"""Python surface of the native runtime.

* ``native_hash_chain`` — drop-in accelerator for the token processor's
  chunk hashing (used automatically when the library is available).
* ``OffloadEngine`` — async host-buffer <-> file jobs on the NUMA-pinned
  native I/O pool, with a pure-Python ThreadPoolExecutor fallback so the
  connector works (slower) without a compiler.

Buffers are passed as numpy arrays; the caller owns their lifetime until
the job completes (enforced here by keeping references until harvest).
"""

from __future__ import annotations

import ctypes
import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from enum import IntEnum
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from llm_d_kv_cache_manager_tpu.native import get_library
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("native.engine")


class JobStatus(IntEnum):
    PENDING = 0
    SUCCEEDED = 1
    FAILED = 2
    UNKNOWN = 3


def native_hash_chain(
    parent_hash: int, tokens: Sequence[int], block_size: int
) -> Optional[List[int]]:
    """Chunk-hash via the native library; None if it is unavailable."""
    lib = get_library()
    if lib is None:
        return None
    try:
        raw = np.asarray(tokens)
        if not np.issubdtype(raw.dtype, np.integer):
            return None
        if raw.dtype != np.uint32 and raw.size and (
            raw.min() < 0 or raw.max() > 0xFFFFFFFF
        ):
            # Out-of-range ids: an unsafe cast would wrap silently and
            # diverge from the arbitrary-precision Python path.
            return None
        token_array = raw.astype(np.uint32, copy=False)
        if not token_array.flags["C_CONTIGUOUS"]:
            token_array = np.ascontiguousarray(token_array)
    except (OverflowError, ValueError, TypeError):
        return None
    n_chunks = len(token_array) // block_size
    if n_chunks == 0:
        return []
    out = np.empty(n_chunks, dtype=np.uint64)
    written = lib.kvtpu_hash_chain(
        ctypes.c_uint64(parent_hash & 0xFFFFFFFFFFFFFFFF),
        token_array.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(token_array),
        block_size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return [int(v) for v in out[:written]]


def store_file(
    path: str, buffer: np.ndarray, skip_existing: bool = True
) -> bool:
    """Synchronous atomic (tmp+rename) store of one host buffer — the
    Python engine's per-file primitive, exposed for callers that need
    a harvest-free write on their own thread (the staged demotion
    target: sharing the async engine's completion stream with the
    connector's ``get_finished`` poll would race the harvest)."""
    try:
        if skip_existing:
            # Dedupe only when the resident file covers at least our
            # bytes; a smaller file is a partial (head) group and is
            # upgraded by rewriting (file = head-k blocks of a
            # group).  If the stat/touch races a sweeper delete,
            # fall through and write the bytes we hold.
            try:
                if os.path.getsize(path) >= buffer.nbytes:
                    os.utime(path)
                    return True
            except OSError:
                pass
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(buffer.tobytes())
        os.replace(tmp, path)
        return True
    except OSError:
        return False


class _PythonEngine:
    """Fallback job engine: ThreadPoolExecutor + Python file I/O."""

    def __init__(self, n_threads: int) -> None:
        self._executor = ThreadPoolExecutor(
            max_workers=n_threads, thread_name_prefix="kvtpu-offload"
        )
        self._lock = lockorder.tracked(
            threading.Lock(), "_PythonEngine._lock"
        )
        self._jobs: Dict[int, List[Future]] = {}

    _store_one = staticmethod(store_file)

    @staticmethod
    def _load_one(path: str, buffer: np.ndarray) -> bool:
        try:
            expected = buffer.nbytes
            # A partial load reads the head of a (possibly larger) group
            # file; a file smaller than the request is a miss.
            if os.path.getsize(path) < expected:
                return False
            with open(path, "rb") as f:
                data = f.read(expected)
            if len(data) != expected:
                return False
            flat = buffer.reshape(-1).view(np.uint8)
            flat[:] = np.frombuffer(data, dtype=np.uint8)
            return True
        except OSError:
            return False

    def store(self, job_id, paths, buffers, skip_existing) -> None:
        futures = [
            self._executor.submit(self._store_one, p, b, skip_existing)
            for p, b in zip(paths, buffers)
        ]
        with self._lock:
            self._jobs[job_id] = futures

    def load(self, job_id, paths, buffers) -> None:
        futures = [
            self._executor.submit(self._load_one, p, b)
            for p, b in zip(paths, buffers)
        ]
        with self._lock:
            self._jobs[job_id] = futures

    def get_finished(self) -> List[Tuple[int, JobStatus]]:
        finished = []
        with self._lock:
            done_ids = [
                job_id
                for job_id, futures in self._jobs.items()
                if all(f.done() for f in futures)
            ]
            for job_id in done_ids:
                futures = self._jobs.pop(job_id)
                ok = all(f.result() for f in futures)
                finished.append(
                    (job_id, JobStatus.SUCCEEDED if ok else JobStatus.FAILED)
                )
        return finished

    def wait(self, job_id) -> JobStatus:
        with self._lock:
            futures = self._jobs.pop(job_id, None)
        if futures is None:
            return JobStatus.UNKNOWN
        ok = all(f.result() for f in futures)
        return JobStatus.SUCCEEDED if ok else JobStatus.FAILED

    def close(self) -> None:
        self._executor.shutdown(wait=True)


class OffloadEngine:
    """Async store/load of host numpy buffers to/from block files."""

    def __init__(self, n_threads: int = 4, numa_node: int = -1) -> None:
        self._lib = get_library()
        self._closed = False
        self.n_threads = n_threads
        self._buffers_lock = lockorder.tracked(
            threading.Lock(), "OffloadEngine._buffers_lock"
        )
        # Keep buffer references alive until their job is harvested.
        self._live_buffers: Dict[int, list] = {}
        if self._lib is not None:
            self._handle = self._lib.kvtpu_engine_create(
                n_threads, numa_node
            )
            self._fallback = None
            logger.info(
                "native offload engine: %d threads, numa_node=%d",
                n_threads,
                numa_node,
            )
        else:
            self._handle = None
            self._fallback = _PythonEngine(n_threads)
            logger.info("python offload engine fallback: %d threads", n_threads)

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError("offload engine is closed")

    def _pin(self, job_id: int, buffers: list) -> None:
        with self._buffers_lock:
            if job_id in self._live_buffers:
                # Overwriting would drop the only references to buffers the
                # native workers still touch (use-after-free).
                raise ValueError(
                    f"job id {job_id} is still in flight; ids must be "
                    "unique until harvested"
                )
            self._live_buffers[job_id] = buffers

    def _unpin(self, job_id: int) -> None:
        with self._buffers_lock:
            self._live_buffers.pop(job_id, None)

    @staticmethod
    def _marshal(paths, buffers):
        n = len(paths)
        path_array = (ctypes.c_char_p * n)(
            *[p.encode() for p in paths]
        )
        ptr_array = (ctypes.c_void_p * n)(
            *[b.ctypes.data_as(ctypes.c_void_p) for b in buffers]
        )
        size_array = (ctypes.c_size_t * n)(*[b.nbytes for b in buffers])
        return path_array, ptr_array, size_array

    def store(
        self,
        job_id: int,
        paths: Sequence[str],
        buffers: Sequence[np.ndarray],
        skip_existing: bool = True,
    ) -> None:
        if len(paths) != len(buffers):
            raise ValueError("paths/buffers length mismatch")
        self._check_open()
        buffers = [np.ascontiguousarray(b) for b in buffers]
        self._pin(job_id, buffers)
        if self._fallback is not None:
            self._fallback.store(job_id, paths, buffers, skip_existing)
            return
        path_array, ptr_array, size_array = self._marshal(paths, buffers)
        self._lib.kvtpu_engine_store(
            self._handle,
            job_id,
            path_array,
            ptr_array,
            size_array,
            len(paths),
            1 if skip_existing else 0,
        )

    def load(
        self,
        job_id: int,
        paths: Sequence[str],
        buffers: Sequence[np.ndarray],
    ) -> None:
        if len(paths) != len(buffers):
            raise ValueError("paths/buffers length mismatch")
        self._check_open()
        for buffer in buffers:
            if not buffer.flags["C_CONTIGUOUS"] or not buffer.flags["WRITEABLE"]:
                raise ValueError("load buffers must be contiguous+writeable")
        buffers = list(buffers)
        self._pin(job_id, buffers)
        if self._fallback is not None:
            self._fallback.load(job_id, paths, buffers)
            return
        path_array, ptr_array, size_array = self._marshal(paths, buffers)
        self._lib.kvtpu_engine_load(
            self._handle,
            job_id,
            path_array,
            ptr_array,
            size_array,
            len(paths),
        )

    def get_finished(self, max_out: int = 1024) -> List[Tuple[int, JobStatus]]:
        self._check_open()
        if self._fallback is not None:
            finished = self._fallback.get_finished()
        else:
            job_ids = (ctypes.c_int64 * max_out)()
            statuses = (ctypes.c_int32 * max_out)()
            n = self._lib.kvtpu_engine_get_finished(
                self._handle, job_ids, statuses, max_out
            )
            finished = [
                (int(job_ids[i]), JobStatus(int(statuses[i])))
                for i in range(n)
            ]
        for job_id, _ in finished:
            self._unpin(job_id)
        return finished

    def wait(self, job_id: int) -> JobStatus:
        self._check_open()
        if self._fallback is not None:
            status = self._fallback.wait(job_id)
        else:
            status = JobStatus(
                int(self._lib.kvtpu_engine_wait(self._handle, job_id))
            )
        self._unpin(job_id)
        return status

    def close(self) -> None:
        if self._closed:
            return
        # gil-atomic: monotonic close flag; double close is idempotent
        self._closed = True
        if self._fallback is not None:
            self._fallback.close()
        elif self._handle is not None:
            self._lib.kvtpu_engine_destroy(self._handle)
            # gil-atomic: close is single-owner; __del__ runs at last ref only
            self._handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
