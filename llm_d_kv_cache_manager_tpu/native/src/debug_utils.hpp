// Env-gated debug logging + phase timing for the native engine.
//
// Capability parity with the reference's debug utils
// (csrc/storage/debug_utils.hpp): set KVTPU_NATIVE_DEBUG=1 to get
// per-phase timing lines on stderr; zero overhead when unset beyond
// one cached getenv check.

#pragma once

#include <chrono>
#include <cstdio>
#include <cstdlib>

namespace kvtpu {

inline bool debug_enabled() {
  static const bool enabled = [] {
    const char* env = std::getenv("KVTPU_NATIVE_DEBUG");
    return env != nullptr && env[0] != '\0' && env[0] != '0';
  }();
  return enabled;
}

#define KVTPU_DEBUG_PRINT(...)                  \
  do {                                          \
    if (::kvtpu::debug_enabled()) {             \
      std::fprintf(stderr, "[kvtpu] " __VA_ARGS__); \
      std::fputc('\n', stderr);                 \
    }                                           \
  } while (0)

// Evaluates expr; when debugging, also logs its wall time under `label`.
#define KVTPU_TIME_EXPR(label, expr)                                     \
  do {                                                                   \
    if (::kvtpu::debug_enabled()) {                                      \
      auto kvtpu_t0 = std::chrono::steady_clock::now();                  \
      expr;                                                              \
      auto kvtpu_us = std::chrono::duration_cast<std::chrono::microseconds>( \
                          std::chrono::steady_clock::now() - kvtpu_t0)   \
                          .count();                                      \
      std::fprintf(stderr, "[kvtpu] %s: %lld us\n", label,               \
                   static_cast<long long>(kvtpu_us));                    \
    } else {                                                             \
      expr;                                                              \
    }                                                                    \
  } while (0)

}  // namespace kvtpu
