// Async offload job engine + C ABI for ctypes.
//
// Job model follows the reference engine (csrc/storage/
// storage_offload.cpp): a job fans out to one task per file on the I/O
// pool; an atomic completion counter resolves the job's future; finished
// jobs are harvested once via get_finished() or awaited via wait().
// Unlike the reference, *failures are counted and reported* — the
// reference silently ignored read failures (its TODOs at :202-204,
// :261-263).

#include <cstring>

#include "debug_utils.hpp"
#include "kvtpu_native.hpp"

namespace kvtpu {

OffloadEngine::OffloadEngine(size_t n_threads, int numa_node)
    : pool_(n_threads, numa_node) {}

std::shared_ptr<OffloadEngine::Job> OffloadEngine::register_job(
    int64_t job_id, size_t n_tasks) {
  auto job = std::make_shared<Job>();
  job->total_tasks = n_tasks;
  job->done_future = job->done.get_future().share();
  std::lock_guard<std::mutex> lock(jobs_mu_);
  jobs_[job_id] = job;
  return job;
}

void OffloadEngine::finish_task(int64_t /*job_id*/,
                                const std::shared_ptr<Job>& job, bool ok) {
  if (!ok) job->failed.fetch_add(1);
  if (job->completed.fetch_add(1) + 1 == job->total_tasks) {
    job->done.set_value();
  }
}

void OffloadEngine::store(int64_t job_id,
                          const std::vector<std::string>& paths,
                          const std::vector<const uint8_t*>& buffers,
                          const std::vector<size_t>& sizes,
                          bool skip_existing) {
  auto job = register_job(job_id, paths.size());
  if (paths.empty()) {
    job->done.set_value();
    return;
  }
  for (size_t i = 0; i < paths.size(); ++i) {
    const std::string path = paths[i];
    const uint8_t* buffer = buffers[i];
    const size_t size = sizes[i];
    pool_.enqueue([this, job_id, job, path, buffer, size, skip_existing] {
      // Another pod already persisted this (or a larger) group:
      // refresh recency so storage sweepers keep it.  A smaller file is
      // a partial head group, upgraded by rewriting.  If the touch
      // races a sweeper delete, fall through and write the bytes we
      // already hold instead of failing the job.
      bool ok = skip_existing &&
                file_size(path) >= static_cast<int64_t>(size) &&
                touch_file(path);
      if (!ok) {
        KVTPU_TIME_EXPR("store:write_file",
                        ok = write_buffer_to_file(path, buffer, size));
      } else {
        KVTPU_DEBUG_PRINT("store:skip_existing %s", path.c_str());
      }
      finish_task(job_id, job, ok);
    });
  }
}

void OffloadEngine::load(int64_t job_id,
                         const std::vector<std::string>& paths,
                         const std::vector<uint8_t*>& buffers,
                         const std::vector<size_t>& sizes) {
  auto job = register_job(job_id, paths.size());
  if (paths.empty()) {
    job->done.set_value();
    return;
  }
  for (size_t i = 0; i < paths.size(); ++i) {
    const std::string path = paths[i];
    uint8_t* buffer = buffers[i];
    const size_t size = sizes[i];
    pool_.enqueue([this, job_id, job, path, buffer, size] {
      bool ok = false;
      KVTPU_TIME_EXPR("load:read_file",
                      ok = read_buffer_from_file(path, buffer, size));
      finish_task(job_id, job, ok);
    });
  }
}

std::vector<std::pair<int64_t, JobStatus>> OffloadEngine::get_finished(
    size_t max_out) {
  std::vector<std::pair<int64_t, JobStatus>> finished;
  std::lock_guard<std::mutex> lock(jobs_mu_);
  for (auto it = jobs_.begin();
       it != jobs_.end() && finished.size() < max_out;) {
    auto& job = it->second;
    if (job->completed.load() == job->total_tasks) {
      finished.emplace_back(it->first, job->failed.load() == 0
                                           ? JobStatus::kSucceeded
                                           : JobStatus::kFailed);
      it = jobs_.erase(it);
    } else {
      ++it;
    }
  }
  return finished;
}

JobStatus OffloadEngine::wait(int64_t job_id) {
  std::shared_ptr<Job> job;
  {
    std::lock_guard<std::mutex> lock(jobs_mu_);
    auto it = jobs_.find(job_id);
    if (it == jobs_.end()) return JobStatus::kUnknown;
    job = it->second;
  }
  job->done_future.wait();
  // Exactly-once claim: a concurrent get_finished() poller may have
  // harvested (erased) the job between our lookup and the future
  // firing.  Only the claimant that removes the map entry reports the
  // status; the loser sees kUnknown, exactly as if it had arrived
  // after the harvest.  (The TSan stress harness, stress_main.cpp,
  // caught the pre-fix double-report.)
  JobStatus status = job->failed.load() == 0 ? JobStatus::kSucceeded
                                             : JobStatus::kFailed;
  {
    std::lock_guard<std::mutex> lock(jobs_mu_);
    if (jobs_.erase(job_id) == 0) return JobStatus::kUnknown;
  }
  return status;
}

}  // namespace kvtpu

// ---------------------------------------------------------------------------
// C ABI (ctypes)
// ---------------------------------------------------------------------------

extern "C" {

uint64_t kvtpu_fnv1a64(const uint8_t* data, size_t len) {
  return kvtpu::fnv1a64(data, len);
}

// Returns the number of keys written (n_tokens / block_size).
size_t kvtpu_hash_chain(uint64_t parent_hash, const uint32_t* tokens,
                        size_t n_tokens, size_t block_size,
                        uint64_t* out_keys) {
  return kvtpu::hash_chain(parent_hash, tokens, n_tokens, block_size,
                           out_keys);
}

void* kvtpu_engine_create(size_t n_threads, int numa_node) {
  return new kvtpu::OffloadEngine(n_threads, numa_node);
}

void kvtpu_engine_destroy(void* engine) {
  delete static_cast<kvtpu::OffloadEngine*>(engine);
}

static std::vector<std::string> collect_paths(const char** paths,
                                              size_t n) {
  std::vector<std::string> out;
  out.reserve(n);
  for (size_t i = 0; i < n; ++i) out.emplace_back(paths[i]);
  return out;
}

void kvtpu_engine_store(void* engine, int64_t job_id, const char** paths,
                        const uint8_t** buffers, const size_t* sizes,
                        size_t n_files, int skip_existing) {
  auto* e = static_cast<kvtpu::OffloadEngine*>(engine);
  e->store(job_id, collect_paths(paths, n_files),
           std::vector<const uint8_t*>(buffers, buffers + n_files),
           std::vector<size_t>(sizes, sizes + n_files),
           skip_existing != 0);
}

void kvtpu_engine_load(void* engine, int64_t job_id, const char** paths,
                       uint8_t** buffers, const size_t* sizes,
                       size_t n_files) {
  auto* e = static_cast<kvtpu::OffloadEngine*>(engine);
  e->load(job_id, collect_paths(paths, n_files),
          std::vector<uint8_t*>(buffers, buffers + n_files),
          std::vector<size_t>(sizes, sizes + n_files));
}

// Fills out_job_ids/out_statuses (capacity max_out); returns count.
// Jobs beyond max_out remain harvestable on the next call.
size_t kvtpu_engine_get_finished(void* engine, int64_t* out_job_ids,
                                 int32_t* out_statuses, size_t max_out) {
  auto* e = static_cast<kvtpu::OffloadEngine*>(engine);
  const auto finished = e->get_finished(max_out);
  const size_t n = finished.size();
  for (size_t i = 0; i < n; ++i) {
    out_job_ids[i] = finished[i].first;
    out_statuses[i] = static_cast<int32_t>(finished[i].second);
  }
  return n;
}

int32_t kvtpu_engine_wait(void* engine, int64_t job_id) {
  auto* e = static_cast<kvtpu::OffloadEngine*>(engine);
  return static_cast<int32_t>(e->wait(job_id));
}

int kvtpu_file_exists(const char* path) {
  return kvtpu::file_exists(path) ? 1 : 0;
}

}  // extern "C"
