// Atomic file persistence for KV blocks on shared storage.
// Write = temp + rename so concurrent pods never observe torn files;
// read validates exact size (reference: csrc/storage/file_io.cpp).

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdio>
#include <filesystem>
#include <fstream>

#include "kvtpu_native.hpp"

namespace kvtpu {

namespace {
std::atomic<uint64_t> g_tmp_counter{0};
}

bool file_exists(const std::string& path) {
  struct stat st;
  return ::stat(path.c_str(), &st) == 0;
}

// -1 when the file does not exist.
int64_t file_size(const std::string& path) {
  struct stat st;
  if (::stat(path.c_str(), &st) != 0) return -1;
  return static_cast<int64_t>(st.st_size);
}

bool write_buffer_to_file(const std::string& path, const uint8_t* data,
                          size_t size) {
  namespace fs = std::filesystem;
  std::error_code ec;
  const fs::path target(path);
  if (target.has_parent_path()) {
    fs::create_directories(target.parent_path(), ec);  // ok if exists
  }

  // Thread-unique temp name in the same directory (rename must not cross
  // filesystems).
  const uint64_t unique = g_tmp_counter.fetch_add(1);
  std::string tmp = path + ".tmp." + std::to_string(::getpid()) + "." +
                    std::to_string(unique);

  {
    std::ofstream out(tmp, std::ios::binary | std::ios::trunc);
    if (!out) return false;
    out.write(reinterpret_cast<const char*>(data),
              static_cast<std::streamsize>(size));
    if (!out) {
      out.close();
      ::unlink(tmp.c_str());
      return false;
    }
  }
  if (::rename(tmp.c_str(), path.c_str()) != 0) {
    ::unlink(tmp.c_str());
    return false;
  }
  return true;
}

bool read_buffer_from_file(const std::string& path, uint8_t* data,
                           size_t size) {
  struct stat st;
  if (::stat(path.c_str(), &st) != 0) return false;
  // Head-of-group semantics: a partial request reads the head of a
  // (possibly larger) group file; a smaller file is a miss.
  if (static_cast<size_t>(st.st_size) < size) return false;
  std::ifstream in(path, std::ios::binary);
  if (!in) return false;
  in.read(reinterpret_cast<char*>(data),
          static_cast<std::streamsize>(size));
  return static_cast<size_t>(in.gcount()) == size;
}

bool touch_file(const std::string& path) {
  // nullptr = set both atime and mtime to now (matches os.utime()).
  // False when the file vanished (store-dedupe racing a sweeper
  // delete): the job must fail rather than advertise a gone block.
  return ::utimensat(AT_FDCWD, path.c_str(), nullptr, 0) == 0;
}

}  // namespace kvtpu
