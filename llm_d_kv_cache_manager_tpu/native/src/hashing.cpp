// Chained CBOR/FNV block hashing — native fast path for the contract
// implemented in kvcache/kvblock/token_processor.py (see its docstring for
// the cross-system semantics; parity is enforced by tests that compare
// this implementation against the Python one).

#include "kvtpu_native.hpp"

namespace kvtpu {

namespace {

constexpr uint64_t kFnvOffset = 0xcbf29ce484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001b3ULL;

// Canonical CBOR head: shortest-form unsigned argument.
void encode_head(uint8_t major, uint64_t value, std::vector<uint8_t>& out) {
  const uint8_t mt = static_cast<uint8_t>(major << 5);
  if (value < 24) {
    out.push_back(mt | static_cast<uint8_t>(value));
  } else if (value < 0x100) {
    out.push_back(mt | 24);
    out.push_back(static_cast<uint8_t>(value));
  } else if (value < 0x10000) {
    out.push_back(mt | 25);
    out.push_back(static_cast<uint8_t>(value >> 8));
    out.push_back(static_cast<uint8_t>(value));
  } else if (value < 0x100000000ULL) {
    out.push_back(mt | 26);
    for (int shift = 24; shift >= 0; shift -= 8)
      out.push_back(static_cast<uint8_t>(value >> shift));
  } else {
    out.push_back(mt | 27);
    for (int shift = 56; shift >= 0; shift -= 8)
      out.push_back(static_cast<uint8_t>(value >> shift));
  }
}

}  // namespace

uint64_t fnv1a64(const uint8_t* data, size_t len) {
  uint64_t h = kFnvOffset;
  for (size_t i = 0; i < len; ++i) {
    h ^= data[i];
    h *= kFnvPrime;
  }
  return h;
}

void encode_chunk_payload(uint64_t parent, const uint32_t* tokens,
                          size_t n_tokens, std::vector<uint8_t>& out) {
  out.push_back(0x83);  // array(3)
  encode_head(0, parent, out);
  encode_head(4, n_tokens, out);  // array(n_tokens)
  for (size_t i = 0; i < n_tokens; ++i) encode_head(0, tokens[i], out);
  out.push_back(0xf6);  // null extra
}

size_t hash_chain(uint64_t parent_hash, const uint32_t* tokens,
                  size_t n_tokens, size_t block_size, uint64_t* out_keys) {
  if (block_size == 0) return 0;
  const size_t n_chunks = n_tokens / block_size;
  uint64_t prefix = parent_hash;
  std::vector<uint8_t> payload;
  payload.reserve(3 + 9 + 5 + 5 * block_size);
  for (size_t c = 0; c < n_chunks; ++c) {
    payload.clear();
    encode_chunk_payload(prefix, tokens + c * block_size, block_size,
                         payload);
    prefix = fnv1a64(payload.data(), payload.size());
    out_keys[c] = prefix;
  }
  return n_chunks;
}

}  // namespace kvtpu
