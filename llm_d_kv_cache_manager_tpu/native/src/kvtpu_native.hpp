// kvtpu native runtime: block hashing + host-side KV offload engine.
//
// TPU-native counterpart of the reference's C++/CUDA storage connector
// (reference: kv_connectors/llmd_fs_backend/csrc/storage/).  The CUDA
// pieces (streams, events, pinned staging, device copies) do not exist on
// TPU — XLA owns device<->host transfers — so this engine's job is
// everything *after* the host buffer: NUMA-aware I/O threading, atomic
// file persistence, async job tracking, and the hot hash chain.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstddef>
#include <cstdint>
#include <deque>
#include <functional>
#include <future>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace kvtpu {

// ---------------------------------------------------------------------------
// Hashing (see token_processor.py for the contract)
// ---------------------------------------------------------------------------

uint64_t fnv1a64(const uint8_t* data, size_t len);

// Appends the canonical-CBOR encoding of [parent, tokens, null] to `out`.
void encode_chunk_payload(uint64_t parent, const uint32_t* tokens,
                          size_t n_tokens, std::vector<uint8_t>& out);

// Chained block hashing: writes one key per full block_size chunk into
// out_keys (capacity n_tokens / block_size), returns the number written.
size_t hash_chain(uint64_t parent_hash, const uint32_t* tokens,
                  size_t n_tokens, size_t block_size, uint64_t* out_keys);

// ---------------------------------------------------------------------------
// NUMA
// ---------------------------------------------------------------------------

// CPUs of a NUMA node, parsed from
// /sys/devices/system/node/node<N>/cpulist; empty if unknown.
std::vector<int> cpus_in_numa_node(int node);

// Pin the calling thread to the given CPUs (no-op on empty/failure).
bool pin_thread_to_cpus(const std::vector<int>& cpus);

// ---------------------------------------------------------------------------
// Thread pool
// ---------------------------------------------------------------------------

class ThreadPool {
 public:
  // numa_node < 0: no pinning. Threads are round-robin pinned to the
  // node's CPUs (reference: csrc/storage/thread_pool.cpp:55-112).
  ThreadPool(size_t n_threads, int numa_node);
  ~ThreadPool();

  void enqueue(std::function<void()> task);
  size_t size() const { return threads_.size(); }

 private:
  void worker(size_t index, int numa_node);

  std::vector<std::thread> threads_;
  std::deque<std::function<void()>> queue_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stopping_ = false;
};

// ---------------------------------------------------------------------------
// File I/O
// ---------------------------------------------------------------------------

// Atomic write: temp file with a thread-unique suffix, then rename()
// (reference: csrc/storage/file_io.cpp:40-99).  Creates parent dirs.
bool write_buffer_to_file(const std::string& path, const uint8_t* data,
                          size_t size);

// Full-file read with exact-size validation
// (reference: csrc/storage/file_io.cpp:103-140).
bool read_buffer_from_file(const std::string& path, uint8_t* data,
                           size_t size);

bool file_exists(const std::string& path);

// Size in bytes, or -1 when the file does not exist.
int64_t file_size(const std::string& path);

// Refresh atime+mtime so recency-based sweepers on shared storage (and
// noatime mounts) see recent use.  The reference intended atime-only but
// actually updated mtime (file_io.cpp:143-148, noted doc/code mismatch);
// we update both deliberately and match the Python fallback.
bool touch_file(const std::string& path);

// ---------------------------------------------------------------------------
// Offload engine
// ---------------------------------------------------------------------------

enum class JobStatus : int32_t {
  kPending = 0,
  kSucceeded = 1,
  kFailed = 2,
  kUnknown = 3,
};

// Async store/load between caller-owned host buffers and files.  One job =
// many file tasks; get_finished() harvests completed jobs like the
// reference engine (csrc/storage/storage_offload.cpp:89-113).
class OffloadEngine {
 public:
  OffloadEngine(size_t n_threads, int numa_node);

  // Buffers must stay alive until the job finishes. skip_existing
  // implements cross-pod dedupe on shared storage.
  void store(int64_t job_id, const std::vector<std::string>& paths,
             const std::vector<const uint8_t*>& buffers,
             const std::vector<size_t>& sizes, bool skip_existing);

  void load(int64_t job_id, const std::vector<std::string>& paths,
            const std::vector<uint8_t*>& buffers,
            const std::vector<size_t>& sizes);

  // Harvest up to max_out finished jobs (each reported once; the rest
  // stay resident for the next poll).
  std::vector<std::pair<int64_t, JobStatus>> get_finished(size_t max_out);

  // Block until a job finishes; returns its status.
  JobStatus wait(int64_t job_id);

 private:
  struct Job {
    size_t total_tasks = 0;
    std::atomic<size_t> completed{0};
    std::atomic<size_t> failed{0};
    std::promise<void> done;
    std::shared_future<void> done_future;
  };

  std::shared_ptr<Job> register_job(int64_t job_id, size_t n_tasks);
  void finish_task(int64_t job_id, const std::shared_ptr<Job>& job,
                   bool ok);

  ThreadPool pool_;
  std::mutex jobs_mu_;
  std::unordered_map<int64_t, std::shared_ptr<Job>> jobs_;
};

}  // namespace kvtpu
