// NUMA discovery via sysfs only (no libnuma dependency): TPU VMs expose
// the standard /sys/devices/system/node layout
// (reference: csrc/storage/numa_utils.cpp:33-118, minus the CUDA query).

#include <pthread.h>
#include <sched.h>

#include <fstream>
#include <sstream>

#include "kvtpu_native.hpp"

namespace kvtpu {

std::vector<int> cpus_in_numa_node(int node) {
  std::vector<int> cpus;
  if (node < 0) return cpus;
  std::ostringstream path;
  path << "/sys/devices/system/node/node" << node << "/cpulist";
  std::ifstream in(path.str());
  if (!in) return cpus;
  std::string list;
  std::getline(in, list);
  // Format: comma-separated ranges, e.g. "0-3,8,10-11".
  std::stringstream ss(list);
  std::string part;
  while (std::getline(ss, part, ',')) {
    if (part.empty()) continue;
    const auto dash = part.find('-');
    try {
      if (dash == std::string::npos) {
        cpus.push_back(std::stoi(part));
      } else {
        const int lo = std::stoi(part.substr(0, dash));
        const int hi = std::stoi(part.substr(dash + 1));
        for (int cpu = lo; cpu <= hi; ++cpu) cpus.push_back(cpu);
      }
    } catch (const std::exception&) {
      return {};
    }
  }
  return cpus;
}

bool pin_thread_to_cpus(const std::vector<int>& cpus) {
  if (cpus.empty()) return false;
  cpu_set_t set;
  CPU_ZERO(&set);
  for (int cpu : cpus) CPU_SET(cpu, &set);
  return pthread_setaffinity_np(pthread_self(), sizeof(set), &set) == 0;
}

}  // namespace kvtpu
