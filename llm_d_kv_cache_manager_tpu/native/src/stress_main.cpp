// ThreadSanitizer stress harness for the offload engine.
//
// SURVEY.md §5 notes the reference wires no race detection at all
// (concurrency safety is by design only); this binary is the TPU
// build's answer: hammer every engine entry point from many threads at
// once and let TSan prove the synchronization. Built and run by
// `python -m llm_d_kv_cache_manager_tpu.native.build --stress`
// (plain) or `--stress-tsan` (with -fsanitize=thread); also runnable
// via `make native-race` and tests/test_native_race.py.
//
// Exercised concurrently:
//   * N producer threads issuing store jobs (disjoint job-id ranges)
//   * N reader threads issuing load jobs for files known to exist
//   * a poller thread draining get_finished() the whole time
//   * waiter threads blocking on specific job ids
// Ends by asserting every job completed exactly once with SUCCEEDED.

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "kvtpu_native.hpp"

namespace {

constexpr int kProducers = 4;
constexpr int kJobsPerProducer = 200;
constexpr size_t kFilesPerJob = 2;
constexpr size_t kBufBytes = 16 * 1024;

std::string tmp_root() {
  const char* env = std::getenv("KVTPU_STRESS_DIR");
  if (env != nullptr) return env;
  char templ[] = "/tmp/kvtpu-stress-XXXXXX";
  char* dir = mkdtemp(templ);
  if (dir == nullptr) {
    std::perror("mkdtemp");
    std::exit(2);
  }
  return dir;
}

}  // namespace

int main() {
  const std::string root = tmp_root();
  kvtpu::OffloadEngine engine(/*n_threads=*/4, /*numa_node=*/-1);

  // Stable per-producer buffers: alive until their jobs are harvested.
  std::vector<std::vector<uint8_t>> buffers(kProducers);
  for (int p = 0; p < kProducers; ++p) {
    buffers[p].assign(kBufBytes, static_cast<uint8_t>(p + 1));
  }

  std::atomic<bool> stop_polling{false};
  std::atomic<int> harvested{0};
  std::atomic<int> failed{0};

  // Poller: drains completions concurrently with submission and wait().
  std::thread poller([&] {
    while (!stop_polling.load()) {
      for (auto& [job_id, status] : engine.get_finished(64)) {
        (void)job_id;
        harvested.fetch_add(1);
        if (status != kvtpu::JobStatus::kSucceeded) failed.fetch_add(1);
      }
      std::this_thread::yield();
    }
  });

  // Producers: store jobs with disjoint id ranges.
  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&, p] {
      for (int j = 0; j < kJobsPerProducer; ++j) {
        const int64_t job_id = p * kJobsPerProducer + j;
        std::vector<std::string> paths;
        std::vector<const uint8_t*> bufs;
        std::vector<size_t> sizes;
        for (size_t f = 0; f < kFilesPerJob; ++f) {
          paths.push_back(root + "/p" + std::to_string(p) + "/f" +
                          std::to_string(j) + "_" + std::to_string(f) +
                          ".bin");
          bufs.push_back(buffers[p].data());
          sizes.push_back(kBufBytes);
        }
        engine.store(job_id, paths, bufs, sizes,
                     /*skip_existing=*/j % 2 == 0);
        if (j % 8 == 0) {
          // Interleave blocking waits with the poller's harvesting;
          // exactly one claimant per completion: wait() returns
          // kUnknown when the poller already erased the job.
          switch (engine.wait(job_id)) {
            case kvtpu::JobStatus::kSucceeded:
              harvested.fetch_add(1);
              break;
            case kvtpu::JobStatus::kUnknown:
              break;  // poller claimed it; it already counted
            default:
              failed.fetch_add(1);
              harvested.fetch_add(1);
          }
        }
      }
    });
  }
  for (auto& t : producers) t.join();

  // Readers: load back files written above, racing the poller.
  std::vector<std::vector<uint8_t>> read_bufs(kProducers);
  std::vector<std::thread> readers;
  for (int p = 0; p < kProducers; ++p) {
    read_bufs[p].resize(kBufBytes);
    readers.emplace_back([&, p] {
      const int64_t job_id = 100000 + p;
      std::vector<std::string> paths = {root + "/p" + std::to_string(p) +
                                        "/f0_0.bin"};
      std::vector<uint8_t*> bufs = {read_bufs[p].data()};
      std::vector<size_t> sizes = {kBufBytes};
      engine.load(job_id, paths, bufs, sizes);
      switch (engine.wait(job_id)) {
        case kvtpu::JobStatus::kSucceeded:
          harvested.fetch_add(1);
          if (read_bufs[p][0] != static_cast<uint8_t>(p + 1)) {
            std::fprintf(stderr, "corrupt readback p%d\n", p);
            std::exit(3);
          }
          break;
        case kvtpu::JobStatus::kUnknown:
          break;  // poller claimed it (and counted it)
        default:
          failed.fetch_add(1);
          harvested.fetch_add(1);
      }
    });
  }
  for (auto& t : readers) t.join();

  // Drain the stragglers, then stop the poller.
  const int total_jobs = kProducers * kJobsPerProducer + kProducers;
  for (int spins = 0; harvested.load() < total_jobs && spins < 10000;
       ++spins) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  stop_polling.store(true);
  poller.join();

  if (harvested.load() != total_jobs || failed.load() != 0) {
    std::fprintf(stderr, "harvested=%d/%d failed=%d\n", harvested.load(),
                 total_jobs, failed.load());
    return 1;
  }
  std::printf("stress ok: %d jobs, 0 failures\n", total_jobs);
  return 0;
}
