// I/O thread pool with per-node CPU pinning.  Same shape as the
// reference's pool (csrc/storage/thread_pool.cpp) minus CUDA streams and
// pinned staging: XLA owns device<->host transfers on TPU, so workers
// only ever touch host memory and files.

#include "kvtpu_native.hpp"

namespace kvtpu {

ThreadPool::ThreadPool(size_t n_threads, int numa_node) {
  if (n_threads == 0) n_threads = 1;
  threads_.reserve(n_threads);
  for (size_t i = 0; i < n_threads; ++i) {
    threads_.emplace_back([this, i, numa_node] { worker(i, numa_node); });
  }
}

ThreadPool::~ThreadPool() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    stopping_ = true;
  }
  cv_.notify_all();
  for (auto& thread : threads_) {
    if (thread.joinable()) thread.join();
  }
}

void ThreadPool::enqueue(std::function<void()> task) {
  {
    std::lock_guard<std::mutex> lock(mu_);
    queue_.push_back(std::move(task));
  }
  cv_.notify_one();
}

void ThreadPool::worker(size_t index, int numa_node) {
  if (numa_node >= 0) {
    const auto cpus = cpus_in_numa_node(numa_node);
    if (!cpus.empty()) {
      // Round-robin across the node's CPUs, one per worker.
      pin_thread_to_cpus({cpus[index % cpus.size()]});
    }
  }
  for (;;) {
    std::function<void()> task;
    {
      std::unique_lock<std::mutex> lock(mu_);
      cv_.wait(lock, [this] { return stopping_ || !queue_.empty(); });
      if (stopping_ && queue_.empty()) return;
      task = std::move(queue_.front());
      queue_.pop_front();
    }
    task();
  }
}

}  // namespace kvtpu
