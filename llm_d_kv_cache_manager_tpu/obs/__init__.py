"""Observability: request-scoped tracing + flight recorder.

See docs/observability.md.  Import surface:

    from llm_d_kv_cache_manager_tpu.obs import (
        TRACER, current_trace, span, use_trace,
    )
"""

from llm_d_kv_cache_manager_tpu.obs.capture import (
    CaptureConfig,
    IncidentManager,
    InputCaptureRecorder,
    capture_enabled_env,
    config_fingerprint,
    encode_capture,
    fingerprint_status,
    set_build_info_metric,
)
from llm_d_kv_cache_manager_tpu.obs.replay import (
    CaptureMismatchError,
    ReplayReport,
    load_capture,
    replay_capture,
)
from llm_d_kv_cache_manager_tpu.obs.whatif import (
    StackConfig,
    WhatIfConfig,
    WhatIfRegistry,
    capture_to_bytes,
    gate_headlines,
    interleave,
    reference_ab,
    repeat,
    run_ab,
    run_whatif,
    scale_pods,
    splice,
    stretch,
)
from llm_d_kv_cache_manager_tpu.obs.profiler import (
    PROFILER,
    ProfilerConfig,
    SamplingProfiler,
    thread_role,
)
from llm_d_kv_cache_manager_tpu.obs.recorder import FlightRecorder
from llm_d_kv_cache_manager_tpu.obs.timeline import (
    GaugeTimeline,
    register_default_series,
)
from llm_d_kv_cache_manager_tpu.obs.slo import (
    SloEngine,
    SloSpec,
    default_fleet_slos,
    envelope_states,
    envelope_violations,
)
from llm_d_kv_cache_manager_tpu.obs.trace import (
    TRACER,
    ParentContext,
    Span,
    Trace,
    Tracer,
    TracerConfig,
    current_trace,
    format_traceparent,
    parse_traceparent,
    span,
    use_trace,
)

__all__ = [
    "CaptureConfig",
    "CaptureMismatchError",
    "IncidentManager",
    "InputCaptureRecorder",
    "ReplayReport",
    "capture_enabled_env",
    "config_fingerprint",
    "fingerprint_status",
    "load_capture",
    "replay_capture",
    "set_build_info_metric",
    "FlightRecorder",
    "GaugeTimeline",
    "PROFILER",
    "ProfilerConfig",
    "SamplingProfiler",
    "register_default_series",
    "thread_role",
    "SloEngine",
    "SloSpec",
    "default_fleet_slos",
    "envelope_states",
    "envelope_violations",
    "StackConfig",
    "WhatIfConfig",
    "WhatIfRegistry",
    "capture_to_bytes",
    "encode_capture",
    "gate_headlines",
    "interleave",
    "reference_ab",
    "repeat",
    "run_ab",
    "run_whatif",
    "scale_pods",
    "splice",
    "stretch",
    "TRACER",
    "ParentContext",
    "Span",
    "Trace",
    "Tracer",
    "TracerConfig",
    "current_trace",
    "format_traceparent",
    "parse_traceparent",
    "span",
    "use_trace",
]
