"""Input flight recorder, config fingerprint, and incident bundler.

The observability planes built so far (traces, SLO envelopes,
profiler, timelines) answer *what happened*; none of them can answer
*run it again*.  This module adds the black-box: a bounded, always-on
recording of the service's two canonical ingress streams, bundled with
every other debug surface the moment an SLO envelope is violated, and
replayable to a first divergence by ``obs/replay.py``
(docs/observability.md "Incident response runbook").

Three pieces:

* :class:`InputCaptureRecorder` — per-source bounded rings over the
  two ingress points: decoded kvevents messages **post shed decision**
  (tapped in ``kvevents/pool.py::Pool.add_tasks``: pod, topic, model,
  seq, seq-gap classification, raw payload bytes, admitted/shed
  disposition) and scored requests (tapped in
  ``kvcache/indexer.py``: model, served token chain, pod filter,
  returned scores).  Records are kept as cheap Python tuples — the
  hot-path cost is one lock hop and an append (the read_path and
  event_storm ``capture_ab`` bench cells pin the end-to-end overhead
  ≤ 3%) — and serialized to canonical CBOR only at ``dump()`` time.
  Rings are bounded by ``CAPTURE_WINDOW_S`` (age) and
  ``CAPTURE_MAX_BYTES`` (estimated bytes, split across sources);
  pruning marks the source ``truncated`` so replay knows final-state
  comparison is off the table.  With ``CAPTURE=0`` nothing is
  constructed at all — no ring, no thread (the recorder never has a
  thread), no per-message branch beyond one ``is None`` check.

* :func:`config_fingerprint` — a stable hash of the resolved
  score-relevant env knobs plus the package version, exported as the
  ``kvtpu_build_info``-style gauge (:func:`set_build_info_metric`),
  shown in ``/healthz``, and stamped into every capture header and
  incident manifest so a replay against mismatched knobs refuses with
  the differing knob names instead of diverging mysteriously.

* :class:`IncidentManager` — subscribes to the SLO engine
  (``SloEngine.add_listener``); on a transition into ``violated`` (or
  ``POST /admin/incident``) it atomically dumps one versioned incident
  directory: the capture window, slow/errored traces, the profiler's
  top table + lock contention, the gauge-timeline rings, the cluster
  rpc panel, the SLO payload that fired, and the config fingerprint.
  Bundles are listed at ``GET /debug/incidents``, rate-limited
  (``INCIDENT_MIN_INTERVAL_S``) and pruned to ``INCIDENT_KEEP``.

Capture wire format (canonical CBOR, ``kvcache/kvblock/cbor_canonical``
— deliberately the same deterministic codec the persistence plane
uses; floats ride as 8-byte big-endian IEEE754 byte strings since the
canonical subset has no float major type):

    ["kvtpu-capture", 1, header, [record, ...], state-or-null]
    header  = [fingerprint, [[knob, value], ...], created_us,
               window_s, max_bytes, [truncated source, ...],
               [[meta key, value], ...]]
    kvevents record = [0, seq, ts_us, pod, topic, model, msg_seq,
                       seq_gap, payload-or-null, disposition]
    score record    = [1, seq, ts_us, model, [token, ...],
                       pod-filter-or-null, [[pod, f64 bytes], ...]]
    state   = [[[request_key, [[pod, tier], ...]], ...],
               [[engine_key, request_key], ...]]   (all sorted)

``seq`` is ONE monotone counter across both sources, so the merged
stream totally orders ingress — replay re-drives it in exactly this
order.  Resync commands (``Pool.enqueue_resync``) are anti-entropy
repairs synthesized by the service, not ingress input, and are
deliberately not recorded (docs/observability.md).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu import __version__
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    decode_canonical,
    encode_canonical,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("obs.capture")

CAPTURE_MAGIC = "kvtpu-capture"
CAPTURE_VERSION = 1

SOURCE_KVEVENTS = "kvevents"
SOURCE_SCORES = "scores"

DEFAULT_WINDOW_S = 300.0
DEFAULT_MAX_BYTES = 32 * 1024 * 1024

# Ring-occupancy gauges are refreshed every this-many appends (and at
# every status()/dump()) — a per-record gauge write would tax the very
# hot paths the ≤3% capture_ab budget protects.
_GAUGE_EVERY = 64

# Capture/IncidentManager locks are leaves: record() does deque
# surgery only; serialization, disk writes, and source callables all
# run outside them.
# kvlint: lock-order: InputCapture._lock ascending
lockorder.declare_ascending("InputCapture._lock")


def _env_flag(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).strip().lower() not in (
        "0",
        "false",
        "off",
        "no",
    )


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


# --------------------------- config fingerprint ---------------------------

# The env knobs whose resolved values change what a replayed stack
# would compute: hash-chain identity, chunking, backend topology, and
# the write-path digest semantics.  Kept to documented knobs
# (docs/configuration.md) on purpose — the fingerprint is a contract
# surface, not a dump of os.environ.
FINGERPRINT_KNOBS: Tuple[str, ...] = (
    "PYTHONHASHSEED",
    "BLOCK_SIZE",
    "MODEL_NAME",
    "INDEX_BACKEND",
    "INDEX_SHARDS",
    "READ_PATH_FAST_LANE",
    "READ_PATH_LOOKUP_CHUNK",
    "READ_PATH_SCORE_MEMO",
    "KVEVENTS_LOCKFREE_DECODE",
    "KVEVENTS_COALESCE_EVENTS",
    "KVEVENTS_DIGEST_MEMO",
    "KVEVENTS_APPLY_BATCH",
    "KVEVENTS_POD_BUDGET",
    "KVEVENTS_POD_FLOW",
    "KVEVENTS_GAP_RESYNC",
    "CLUSTER_REPLICAS",
    "CLUSTER_SELF",
    "CLUSTER_MEMBERS",
)


def fingerprint_knobs() -> List[Tuple[str, str]]:
    """The resolved ``(knob, value)`` pairs the fingerprint hashes
    (unset knobs report the empty string so set-to-default and unset
    hash identically only when they really are the same value)."""
    return [
        (name, os.environ.get(name, "")) for name in FINGERPRINT_KNOBS
    ]


def config_fingerprint(
    knobs: Optional[Sequence[Tuple[str, str]]] = None,
) -> str:
    """16-hex-char blake2b over package version + resolved knobs."""
    digest = hashlib.blake2b(digest_size=8)
    digest.update(__version__.encode())
    for name, value in knobs if knobs is not None else fingerprint_knobs():
        digest.update(b"\x00")
        digest.update(str(name).encode())
        digest.update(b"\x01")
        digest.update(str(value).encode())
    return digest.hexdigest()


def fingerprint_status() -> dict:
    """The /healthz + incident-manifest fingerprint block."""
    knobs = fingerprint_knobs()
    return {
        "version": __version__,
        "fingerprint": config_fingerprint(knobs),
        "knobs": {name: value for name, value in knobs if value},
    }


def set_build_info_metric() -> str:
    """Publish ``kvtpu_build_info{version,fingerprint} = 1`` (the
    kube-style build-info gauge) and return the fingerprint."""
    fingerprint = config_fingerprint()
    METRICS.build_info.labels(
        version=__version__, fingerprint=fingerprint
    ).set(1)
    return fingerprint


def diff_knobs(
    recorded: Sequence[Sequence],
    current: Optional[Sequence[Tuple[str, str]]] = None,
) -> List[str]:
    """Human-readable knob differences between a capture header and
    this process — what the replay mismatch error names."""
    current_map = dict(current if current is not None else fingerprint_knobs())
    recorded_map = {str(k): str(v) for k, v in recorded}
    out = []
    for name in sorted(set(recorded_map) | set(current_map)):
        want = recorded_map.get(name, "")
        have = current_map.get(name, "")
        if want != have:
            out.append(f"{name}: recorded {want!r} vs current {have!r}")
    return out


# ------------------------------- float codec -------------------------------


def encode_f64(value: float) -> bytes:
    """Float as 8 big-endian IEEE754 bytes (canonical CBOR here has no
    float major type; byte strings round-trip bit-exactly)."""
    return struct.pack(">d", float(value))


def decode_f64(raw: bytes) -> float:
    return struct.unpack(">d", bytes(raw))[0]


# ------------------------------ capture rings ------------------------------


@dataclass
class CaptureConfig:
    """Knobs for the input flight recorder (docs/configuration.md §9:
    ``CAPTURE``, ``CAPTURE_WINDOW_S``, ``CAPTURE_MAX_BYTES``)."""

    window_s: float = DEFAULT_WINDOW_S
    max_bytes: int = DEFAULT_MAX_BYTES

    @classmethod
    def from_env(cls) -> "CaptureConfig":
        return cls(
            window_s=_env_float("CAPTURE_WINDOW_S", DEFAULT_WINDOW_S),
            max_bytes=_env_int("CAPTURE_MAX_BYTES", DEFAULT_MAX_BYTES),
        )


def capture_enabled_env() -> bool:
    """The CAPTURE knob (default on).  When off, the service wires NO
    recorder anywhere — zero allocation, zero per-message branch
    beyond one ``is None`` check (pinned by tests)."""
    return _env_flag("CAPTURE", "1")


class _SourceRing:
    """One source's bounded record ring (caller holds the recorder
    lock for every method)."""

    __slots__ = ("records", "bytes", "budget", "dropped", "appended")

    def __init__(self, budget: int) -> None:
        self.records: deque = deque()
        self.bytes = 0
        self.budget = budget
        self.dropped = 0
        self.appended = 0

    def append(self, record: tuple, horizon_us: int) -> None:
        self.records.append(record)
        self.bytes += _record_size(record)
        self.appended += 1
        self.prune(horizon_us)

    def prune(self, horizon_us: int) -> None:
        while self.records and (
            self.bytes > self.budget
            or self.records[0][2] < horizon_us
        ):
            old = self.records.popleft()
            self.bytes -= _record_size(old)
            self.dropped += 1


def _record_size(record: tuple) -> int:
    """Cheap size estimate for ring accounting (tokens count 9 bytes
    each — the worst-case canonical uint head; payloads their length).
    Estimation, not truth: the budget bounds memory order-of-magnitude,
    not byte-exactly (docs/observability.md).  Kvevents records come
    in two shapes: the compact admitted form ``(0, seq, ts, message)``
    and the expanded 10-element form (shed paths, single-record
    API)."""
    if record[0] == 0:
        if len(record) == 4:
            message = record[3]
            return 64 + len(message.topic) + len(
                message.capture_payload
            )
        payload = record[8]
        return 64 + (len(payload) if payload is not None else 0) + len(
            record[4]
        )
    tokens = record[4]
    scores = record[6]
    return 64 + 9 * len(tokens) + 24 * len(scores)


class InputCaptureRecorder:
    """Always-on bounded recording of the two ingress streams.

    Thread-safe; one leaf lock.  Records are raw tuples in memory
    (see the module docstring for the wire layout they serialize to):
    the kvevents tap stashes the raw payload BY REFERENCE (a pinned
    zero-copy ZMQ frame costs its own bytes, which is exactly what
    the ring budget bounds) and the scoring tap stores the served
    token list by reference (per-request, never mutated after
    scoring) — both are O(1) appends on the hot path; payloads
    materialize to ``bytes`` only at dump time.
    """

    def __init__(
        self,
        config: Optional[CaptureConfig] = None,
        meta: Optional[Dict[str, object]] = None,
    ) -> None:
        self.config = config or CaptureConfig()
        if self.config.max_bytes <= 0:
            raise ValueError("capture max_bytes must be positive")
        if self.config.window_s <= 0:
            raise ValueError("capture window_s must be positive")
        # Replay-relevant stack facts the embedding application knows
        # (block_size, hash_seed, model) — stamped into the header so
        # obs/replay.py can construct a matching fresh stack.
        self.meta: Dict[str, object] = dict(meta or {})
        self._knobs = fingerprint_knobs()
        self._fingerprint = config_fingerprint(self._knobs)
        self._lock = lockorder.tracked(
            threading.Lock(), "InputCapture._lock"
        )
        budget = max(1, self.config.max_bytes // 2)
        self._rings: Dict[str, _SourceRing] = {  # guarded-by: _lock
            SOURCE_KVEVENTS: _SourceRing(budget),
            SOURCE_SCORES: _SourceRing(budget),
        }
        self._seq = 0  # guarded-by: _lock
        self._gauges = {
            source: METRICS.capture_ring_bytes.labels(source=source)
            for source in self._rings
        }
        self._counters = {
            source: METRICS.capture_records.labels(source=source)
            for source in self._rings
        }
        self._pending_counts = {  # guarded-by: _lock
            source: 0 for source in self._rings
        }

    # -- hot-path appends ----------------------------------------------

    def _append(self, source: str, record_tail: tuple, now_us: int):
        """Allocate the global seq, append, prune, and (sampled)
        refresh the metrics — the single-record hot-path body."""
        horizon = now_us - int(self.config.window_s * 1e6)
        flush = None
        with self._lock:
            ring = self._rings[source]
            self._seq += 1
            record = (record_tail[0], self._seq, now_us) + record_tail[1:]
            ring.append(record, horizon)
            self._pending_counts[source] += 1
            if self._seq % _GAUGE_EVERY == 0:
                flush = {
                    name: (r.bytes, self._pending_counts[name])
                    for name, r in self._rings.items()
                }
                for name in self._pending_counts:
                    self._pending_counts[name] = 0
        if flush is not None:
            self._flush_metrics(flush)

    def _flush_metrics(self, flush: Dict[str, Tuple[int, int]]) -> None:
        for name, (ring_bytes, appended) in flush.items():
            self._gauges[name].set(ring_bytes)
            if appended:
                self._counters[name].inc(appended)

    def record_kvevents(
        self,
        pod: str,
        topic: str,
        model: str,
        seq: int,
        seq_gap: int,
        payload: Optional[bytes],
        disposition: str,
    ) -> None:
        """One wire message post shed decision.  ``disposition`` is
        ``"admitted"`` or the shed reason; a message admitted earlier
        and displaced later appears TWICE (admitted, then shed) — the
        honest stream, reconciled by replay."""
        self.record_kvevents_batch(
            ((pod, topic, model, seq, seq_gap, payload, disposition),)
        )

    def record_kvevents_batch(self, items) -> None:
        """One enqueue burst of wire messages, recorded under ONE lock
        round trip with one shared timestamp — the pool's batched tap
        (``Pool.add_tasks`` drains sockets in bursts of ~64; a
        per-message lock hop here would tax the apply path the
        event_storm ``capture_ab`` bound protects).  ``items`` are
        ``(pod, topic, model, seq, seq_gap, payload, disposition)``
        tuples in burst order."""
        if not items:
            return
        now_us = time.time_ns() // 1000
        horizon = now_us - int(self.config.window_s * 1e6)
        flush = None
        with self._lock:
            ring = self._rings[SOURCE_KVEVENTS]
            seq = self._seq
            rec_append = ring.records.append
            size = 0
            for pod, topic, model, mseq, gap, payload, disp in items:
                seq += 1
                rec_append(
                    (0, seq, now_us, pod, topic, model, int(mseq),
                     int(gap), payload, disp)
                )
                size += 64 + len(topic) + (
                    len(payload) if payload is not None else 0
                )
            self._seq = seq
            ring.bytes += size
            ring.appended += len(items)
            # One prune pass per burst (a burst may overshoot the
            # byte budget by its own size before it, which is noise
            # next to the estimation error the budget already has).
            ring.prune(horizon)
            flush = self._note_pending_locked(
                SOURCE_KVEVENTS, len(items)
            )
        if flush is not None:
            self._flush_metrics(flush)

    def record_admitted_messages(self, messages) -> None:
        """The pool's common-case burst tap: nothing was shed, every
        message is ``admitted``.  The ring holds the Message objects
        themselves in COMPACT records ``(0, seq, ts_us, message)`` —
        zero per-message allocation beyond one 4-tuple — expanded to
        the wire layout only at dump time.  Each message must carry
        ``capture_payload`` (the raw payload stashed before pre-decode
        cleared it) plus the usual pod_identifier / topic /
        model_name / seq / seq_gap attributes."""
        if not messages:
            return
        now_us = time.time_ns() // 1000
        horizon = now_us - int(self.config.window_s * 1e6)
        flush = None
        with self._lock:
            ring = self._rings[SOURCE_KVEVENTS]
            seq = self._seq
            rec_append = ring.records.append
            size = 0
            for message in messages:
                seq += 1
                rec_append((0, seq, now_us, message))
                size += 64 + len(message.topic) + len(
                    message.capture_payload
                )
            self._seq = seq
            ring.bytes += size
            ring.appended += len(messages)
            ring.prune(horizon)
            flush = self._note_pending_locked(
                SOURCE_KVEVENTS, len(messages)
            )
        if flush is not None:
            self._flush_metrics(flush)

    def _note_pending_locked(self, source: str, count: int):
        """Batched metrics bookkeeping (caller holds the lock);
        returns the flush payload when due."""
        pending = self._pending_counts
        pending[source] += count
        if pending[source] < _GAUGE_EVERY:
            return None
        flush = {
            name: (ring.bytes, pending[name])
            for name, ring in self._rings.items()
        }
        for name in pending:
            pending[name] = 0
        return flush

    def record_score(
        self,
        model: str,
        tokens: Sequence[int],
        pods: Optional[Sequence[str]],
        scores: Dict[str, float],
    ) -> None:
        """One scored request: the served token chain (the black-box
        input — chat templating and prefix-store truncation already
        applied), the pod filter, and the returned scores."""
        self._append(
            SOURCE_SCORES,
            (1, model, tokens, tuple(pods) if pods else None, scores),
            time.time_ns() // 1000,
        )

    # -- read side ------------------------------------------------------

    def status(self) -> dict:
        """Occupancy for /debug/incidents, /healthz and the beat."""
        with self._lock:
            rings = {
                name: {
                    "records": len(ring.records),
                    "bytes": ring.bytes,
                    "dropped": ring.dropped,
                    "appended": ring.appended,
                    "truncated": ring.dropped > 0,
                }
                for name, ring in self._rings.items()
            }
            seq = self._seq
        for name, view in rings.items():
            self._gauges[name].set(view["bytes"])
        return {
            "enabled": True,
            "window_s": self.config.window_s,
            "max_bytes": self.config.max_bytes,
            "records": seq,
            "fingerprint": self._fingerprint,
            "sources": rings,
        }

    def _snapshot_merged(self) -> Tuple[List[tuple], List[str]]:
        with self._lock:
            merged: List[tuple] = []
            truncated = [
                name
                for name, ring in self._rings.items()
                if ring.dropped > 0
            ]
            for ring in self._rings.values():
                merged.extend(ring.records)
        merged.sort(key=lambda record: record[1])
        return merged, sorted(truncated)

    def dump_bytes(self, index=None) -> bytes:
        """Serialize the current window to the canonical-CBOR artifact
        (module docstring).  ``index`` adds the canonicalized
        ``dump_entries`` state section — the replay harness compares
        final state against it only when no source was truncated."""
        merged, truncated = self._snapshot_merged()
        records = []
        for record in merged:
            if record[0] == 0:
                if len(record) == 4:
                    # Compact admitted form: expand from the retained
                    # Message (payload materialized to bytes here —
                    # zero-copy memoryviews ride the ring as-is).
                    message = record[3]
                    records.append(
                        [
                            0,
                            record[1],
                            record[2],
                            message.pod_identifier,
                            message.topic,
                            message.model_name,
                            int(message.seq),
                            int(message.seq_gap),
                            bytes(message.capture_payload),
                            "admitted",
                        ]
                    )
                    continue
                expanded = list(record)
                if expanded[8] is not None:
                    expanded[8] = bytes(expanded[8])
                records.append(expanded)
            else:
                kind, seq, ts_us, model, tokens, pods, scores = record
                records.append(
                    [
                        1,
                        seq,
                        ts_us,
                        model,
                        list(tokens),
                        list(pods) if pods is not None else None,
                        [
                            [pod, encode_f64(scores[pod])]
                            for pod in sorted(scores)
                        ],
                    ]
                )
        header = [
            self._fingerprint,
            [list(pair) for pair in self._knobs],
            time.time_ns() // 1000,
            int(self.config.window_s),
            int(self.config.max_bytes),
            truncated,
            [
                [str(key), str(value)]
                for key, value in sorted(self.meta.items())
            ],
        ]
        state = canonical_state(index) if index is not None else None
        return encode_canonical(
            [CAPTURE_MAGIC, CAPTURE_VERSION, header, records, state]
        )

    def dump(self, path: str, index=None) -> int:
        """Write the artifact atomically (tmp + rename); returns its
        size in bytes."""
        payload = self.dump_bytes(index=index)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as handle:
            handle.write(payload)
        os.replace(tmp, path)
        return len(payload)

    def clear(self) -> None:
        """Drop all retained records (test isolation)."""
        with self._lock:
            for ring in self._rings.values():
                ring.records.clear()
                ring.bytes = 0
                ring.dropped = 0


def canonical_state(index) -> list:
    """Order-independent form of ``Index.dump_entries`` — pod-entry
    sets merged + sorted per key, keys sorted, engine map deduped +
    sorted — so two runs whose cross-pod applies interleaved
    differently (but commuted) compare equal, and a cluster
    ``RemoteIndex`` dump (which legitimately reports a key once per
    owning replica) compares equal to a single-index dump."""
    block_entries, engine_map = index.dump_entries()
    merged: Dict[int, set] = {}
    for key, pods in block_entries:
        bucket = merged.setdefault(int(key), set())
        bucket.update(
            (entry.pod_identifier, entry.device_tier) for entry in pods
        )
    return [
        [
            [key, [[pod, tier] for pod, tier in sorted(entries)]]
            for key, entries in sorted(merged.items())
        ],
        sorted(
            [ek, rk]
            for ek, rk in {
                (int(ek), int(rk)) for ek, rk in engine_map
            }
        ),
    ]


def load_artifact(data: bytes) -> dict:
    """Decode + structurally validate a capture artifact; returns
    ``{fingerprint, knobs, created_us, window_s, max_bytes, truncated,
    meta, records, state}``.  Raises ``ValueError`` on anything that
    is not a well-formed v1 capture."""
    doc = decode_canonical(bytes(data))
    if (
        not isinstance(doc, list)
        or len(doc) != 5
        or doc[0] != CAPTURE_MAGIC
    ):
        raise ValueError("not a kvtpu capture artifact")
    if doc[1] != CAPTURE_VERSION:
        raise ValueError(f"unsupported capture version {doc[1]!r}")
    header, records, state = doc[2], doc[3], doc[4]
    if not isinstance(header, list) or len(header) < 7:
        raise ValueError("malformed capture header")
    return {
        "fingerprint": str(header[0]),
        "knobs": [(str(k), str(v)) for k, v in header[1]],
        "created_us": int(header[2]),
        "window_s": int(header[3]),
        "max_bytes": int(header[4]),
        "truncated": [str(s) for s in header[5]],
        "meta": {str(k): str(v) for k, v in header[6]},
        "records": records,
        "state": state,
    }


def encode_capture(
    records: Sequence[list],
    fingerprint: Optional[str] = None,
    knobs: Optional[Sequence[Sequence]] = None,
    created_us: int = 0,
    window_s: int = 0,
    max_bytes: int = 0,
    truncated: Optional[Sequence[str]] = None,
    meta: Optional[Dict[str, object]] = None,
    state: Optional[list] = None,
) -> bytes:
    """Serialize already-shaped records to a valid v1 capture artifact
    — the writer for SYNTHETIC captures (the what-if engine's
    composition operators and the pinned reference generator,
    obs/whatif.py / hack/make_reference_capture.py).  ``records`` must
    be fully expanded wire-shape rows (the forms ``load_artifact``
    returns); ``knobs`` defaults to this process's resolved knob set
    and ``fingerprint`` to its hash, so a synthetic artifact replays
    under the same mismatch contract as a recorded one."""
    if knobs is None:
        knobs = fingerprint_knobs()
    knobs = [[str(k), str(v)] for k, v in knobs]
    if fingerprint is None:
        fingerprint = config_fingerprint(
            [(k, v) for k, v in knobs]
        )
    header = [
        str(fingerprint),
        knobs,
        int(created_us),
        int(window_s),
        int(max_bytes),
        [str(s) for s in (truncated or [])],
        [
            [str(key), str(value)]
            for key, value in sorted((meta or {}).items())
        ],
    ]
    return encode_canonical(
        [CAPTURE_MAGIC, CAPTURE_VERSION, header, list(records), state]
    )


# ----------------------------- incident bundler ----------------------------

DEFAULT_INCIDENT_KEEP = 8
DEFAULT_INCIDENT_MIN_INTERVAL_S = 60.0

# kvlint: lock-order: IncidentManager._lock ascending
lockorder.declare_ascending("IncidentManager._lock")


class IncidentManager:
    """Turns a live anomaly into one on-disk incident bundle.

    ``sources`` maps surface name -> zero-arg callable returning a
    JSON-serializable payload (traces, profile, timeline, cluster,
    slo...); each is written as ``<name>.json`` inside the bundle and
    a failing source records its error instead of killing the bundle.
    The capture window is written as ``capture.cbor`` (with the live
    index's canonical state when ``index`` is wired).  Bundles land
    atomically (``<id>.tmp`` → rename) under ``directory`` and are
    pruned oldest-first past ``keep``.
    """

    def __init__(
        self,
        directory: str,
        capture: Optional[InputCaptureRecorder] = None,
        sources: Optional[Dict[str, Callable[[], object]]] = None,
        index=None,
        keep: int = DEFAULT_INCIDENT_KEEP,
        min_interval_s: float = DEFAULT_INCIDENT_MIN_INTERVAL_S,
    ) -> None:
        if keep <= 0:
            raise ValueError("incident keep must be positive")
        self.directory = directory
        self.capture = capture
        self.sources = dict(sources or {})
        self.index = index
        self.keep = keep
        self.min_interval_s = min_interval_s
        self._lock = lockorder.tracked(
            threading.Lock(), "IncidentManager._lock"
        )
        self._counter = 0  # guarded-by: _lock
        self._last_trigger = 0.0  # guarded-by: _lock
        self._last_id: Optional[str] = None  # guarded-by: _lock
        os.makedirs(directory, exist_ok=True)

    # -- triggering -----------------------------------------------------

    def slo_listener(self) -> Callable[[str, str, dict], None]:
        """The callback to hand ``SloEngine.add_listener``: bundles on
        every transition INTO ``violated`` (rate-limited)."""

        def on_transition(old: str, new: str, payload: dict) -> None:
            if new != "violated" or old == "violated":
                return
            bad = sorted(
                name
                for name, view in (payload.get("slis") or {}).items()
                if view.get("state") == "violated"
            )
            self.trigger("slo:" + (",".join(bad) or "overall"))

        return on_transition

    def trigger(self, reason: str, force: bool = False) -> Optional[dict]:
        """Write one bundle; returns its manifest, or None when
        rate-limited (``force`` — the admin endpoint — bypasses)."""
        now = time.time()
        with self._lock:
            if (
                not force
                and now - self._last_trigger < self.min_interval_s
            ):
                logger.warning(
                    "incident trigger %r rate-limited (last bundle "
                    "%.1fs ago, min interval %.1fs)",
                    reason,
                    now - self._last_trigger,
                    self.min_interval_s,
                )
                return None
            self._last_trigger = now
            self._counter += 1
            counter = self._counter
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(now))
        incident_id = f"inc-{stamp}-{counter:04d}"
        try:
            manifest = self._write_bundle(incident_id, reason, now)
        except Exception:  # noqa: BLE001 — an incident must not cascade
            logger.exception("incident bundle %s failed", incident_id)
            METRICS.incident_bundles.labels(outcome="failed").inc()
            return None
        with self._lock:
            self._last_id = incident_id
        METRICS.incident_bundles.labels(outcome="ok").inc()
        self._prune()
        logger.warning(
            "incident bundle %s written (%s): %s",
            incident_id,
            reason,
            os.path.join(self.directory, incident_id),
        )
        return manifest

    def _write_bundle(
        self, incident_id: str, reason: str, now: float
    ) -> dict:
        tmp_dir = os.path.join(self.directory, f"{incident_id}.tmp")
        try:
            return self._write_bundle_into(
                tmp_dir, incident_id, reason, now
            )
        finally:
            # On success os.replace already moved tmp_dir away (this
            # is a no-op); on ANY failure the partial bundle must not
            # squat under INCIDENT_DIR — a disk-full incident is
            # exactly when orphaned multi-MB tmp dirs hurt most.
            shutil.rmtree(tmp_dir, ignore_errors=True)

    def _write_bundle_into(
        self, tmp_dir: str, incident_id: str, reason: str, now: float
    ) -> dict:
        final_dir = os.path.join(self.directory, incident_id)
        os.makedirs(tmp_dir, exist_ok=True)
        files: List[str] = []
        capture_stats = None
        if self.capture is not None:
            size = 0
            payload = self.capture.dump_bytes(index=self.index)
            with open(os.path.join(tmp_dir, "capture.cbor"), "wb") as out:
                out.write(payload)
                size = len(payload)
            files.append("capture.cbor")
            capture_stats = dict(
                self.capture.status(), artifact_bytes=size
            )
        source_errors: Dict[str, str] = {}
        for name, source in sorted(self.sources.items()):
            try:
                payload = source()
            except Exception as exc:  # noqa: BLE001 — bundle what works
                logger.exception("incident source %s failed", name)
                source_errors[name] = repr(exc)
                continue
            file_name = f"{name}.json"
            with open(os.path.join(tmp_dir, file_name), "w") as out:
                json.dump(payload, out, default=str)
            files.append(file_name)
        manifest = {
            "id": incident_id,
            "version": CAPTURE_VERSION,
            "reason": reason,
            "created_unix": now,
            "fingerprint": fingerprint_status(),
            "files": sorted(files),
            "capture": capture_stats,
        }
        if source_errors:
            manifest["source_errors"] = source_errors
        with open(os.path.join(tmp_dir, "manifest.json"), "w") as out:
            json.dump(manifest, out, default=str)
        os.replace(tmp_dir, final_dir)
        return manifest

    def _prune(self) -> None:
        bundles = self._bundle_dirs()
        for stale in bundles[: max(0, len(bundles) - self.keep)]:
            shutil.rmtree(
                os.path.join(self.directory, stale), ignore_errors=True
            )

    def _bundle_dirs(self) -> List[str]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(
            name
            for name in names
            if name.startswith("inc-")
            and not name.endswith(".tmp")
            and os.path.isdir(os.path.join(self.directory, name))
        )

    # -- read side ------------------------------------------------------

    def last_incident_id(self) -> Optional[str]:
        with self._lock:
            return self._last_id

    def list(self) -> List[dict]:
        """Manifests of every retained bundle, newest first (the
        ``GET /debug/incidents`` payload)."""
        out: List[dict] = []
        for name in reversed(self._bundle_dirs()):
            manifest_path = os.path.join(
                self.directory, name, "manifest.json"
            )
            try:
                with open(manifest_path) as handle:
                    out.append(json.load(handle))
            except (OSError, ValueError) as exc:
                out.append({"id": name, "error": f"unreadable: {exc}"})
        return out

    def detail(self, incident_id: str) -> Optional[dict]:
        """One bundle's manifest + on-disk source inventory (the
        ``GET /debug/incidents/<id>`` payload): every file with its
        byte size, so forensics knows what a bundle actually holds
        before pulling multi-MB captures.  ``None`` for unknown or
        malformed ids (path separators never traverse)."""
        if (
            not incident_id
            or not incident_id.startswith("inc-")
            or incident_id != os.path.basename(incident_id)
        ):
            return None
        bundle_dir = os.path.join(self.directory, incident_id)
        if not os.path.isdir(bundle_dir):
            return None
        manifest: dict
        try:
            with open(os.path.join(bundle_dir, "manifest.json")) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as exc:
            manifest = {"id": incident_id, "error": f"unreadable: {exc}"}
        inventory = []
        try:
            names = sorted(os.listdir(bundle_dir))
        except OSError:
            names = []
        for name in names:
            path = os.path.join(bundle_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                size = -1
            inventory.append({"file": name, "bytes": size})
        return {
            "id": incident_id,
            "directory": bundle_dir,
            "manifest": manifest,
            "inventory": inventory,
        }

    def status(self) -> dict:
        bundles = self._bundle_dirs()
        return {
            "directory": self.directory,
            "bundles": len(bundles),
            "keep": self.keep,
            "min_interval_s": self.min_interval_s,
            "last_incident": self.last_incident_id()
            or (bundles[-1] if bundles else None),
        }
