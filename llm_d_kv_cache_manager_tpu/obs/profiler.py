"""Always-on sampling wall-clock profiler (docs/observability.md
"Continuous profiling").

One daemon sampler thread wakes ``PROFILE_HZ`` times a second, grabs
``sys._current_frames()`` (a GIL-atomic snapshot of every live
thread's top frame) and folds each thread's stack into a bounded
collapsed-stack table keyed by *thread role* — the ``kvtpu-<role>``
prefix every worker/poller/sweeper thread in this codebase carries.
That answers "where does wall time go across poller/worker/RPC
threads" continuously, not per-incident:

* wall-clock, not CPU: a thread blocked in ``zmq.poll``, a lock
  acquire, or a replica RPC is sampled exactly like a computing one —
  convoys and sequential fan-outs show up as big blocking frames;
* bounded: at most ``max_stacks`` distinct folded stacks are kept
  (overflow folds into a per-role ``<other>`` bucket, counted), depth
  capped at ``MAX_DEPTH`` frames, so weeks of always-on sampling
  cannot grow memory;
* cheap: the only cost when armed is the sampler thread itself —
  application threads never execute a single added instruction.
  ``PROFILE_HZ=0`` never starts the thread; the module is inert.

Exports the standard collapsed/folded flamegraph format
(``role;frame;frame... N`` — feed it to flamegraph.pl / speedscope)
and a top-N self-time table, both behind ``GET /debug/profile``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("obs.profiler")

DEFAULT_HZ = 19.0  # prime-ish: avoids aliasing with 1s/50ms periodic work
DEFAULT_MAX_STACKS = 4096
MAX_DEPTH = 48

_ROLE_PREFIX = "kvtpu-"


def thread_role(name: str) -> str:
    """Stable role of a thread name: ``kvtpu-events-3`` -> ``events``,
    ``kvtpu-evplane-poller-0`` -> ``evplane-poller``, and the
    ``ThreadPoolExecutor`` shape ``kvtpu-grpc_0`` -> ``grpc`` (its
    ``thread_name_prefix`` threads are named ``<prefix>_<n>``); the
    main thread is ``main``; anything else keeps its name under
    ``other:`` so an unnamed thread is visible (and countable)
    instead of hidden."""
    if name.startswith(_ROLE_PREFIX):
        role = name[len(_ROLE_PREFIX):]
        for sep in ("-", "_"):
            head, _, tail = role.rpartition(sep)
            if head and tail.isdigit():
                return head
        return role
    if name == "MainThread":
        return "main"
    return f"other:{name}"


def is_attributed(name: str) -> bool:
    """True when the thread carries a stable ``kvtpu-`` role name."""
    return name.startswith(_ROLE_PREFIX)


def _frame_label(frame) -> str:
    """``pkg/module.py:func`` — the last two path components keep
    same-named files (pool.py exists three times) distinguishable."""
    code = frame.f_code
    path = code.co_filename
    head, base = os.path.split(path)
    parent = os.path.basename(head)
    if parent:
        base = f"{parent}/{base}"
    return f"{base}:{code.co_name}"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        value = float(raw)
        if value < 0:
            raise ValueError(raw)
        return value
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        value = int(raw)
        if value <= 0:
            raise ValueError(raw)
        return value
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


@dataclass
class ProfilerConfig:
    # Samples per second; 0 disables (start() is a no-op — the
    # PROFILE_HZ=0 path is bit-identical to not having a profiler).
    hz: float = DEFAULT_HZ
    # Bound on distinct folded stacks kept; overflow folds into a
    # per-role "<other>" bucket so the table never grows past this.
    max_stacks: int = DEFAULT_MAX_STACKS

    @classmethod
    def from_env(cls) -> "ProfilerConfig":
        return cls(
            hz=_env_float("PROFILE_HZ", DEFAULT_HZ),
            max_stacks=_env_int("PROFILE_MAX_STACKS", DEFAULT_MAX_STACKS),
        )


class SamplingProfiler:
    """Folded-stack aggregation over a single sampler thread."""

    def __init__(self, config: Optional[ProfilerConfig] = None) -> None:
        self.config = config or ProfilerConfig.from_env()
        self._lock = threading.Lock()
        # folded stack (role, frame, frame, ...) -> sample count.
        self._stacks: Dict[Tuple[str, ...], int] = {}  # guarded-by: _lock
        self._role_samples: Dict[str, int] = {}  # guarded-by: _lock
        self._samples = 0  # guarded-by: _lock
        self._attributed = 0  # guarded-by: _lock
        self._overflowed = 0  # guarded-by: _lock
        self._wakeups = 0  # guarded-by: _lock
        self._started_at: Optional[float] = None  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> bool:
        """Spawn the sampler thread; False (and no thread, no cost)
        when ``hz`` is 0.  Idempotent while running."""
        if self.config.hz <= 0:
            return False
        if self._thread is not None and self._thread.is_alive():
            return True
        self._stop.clear()
        with self._lock:
            self._started_at = time.time()
        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=self._run, name="kvtpu-profiler", daemon=True
        )
        self._thread.start()
        return True

    def close(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None

    def running(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    def reset(self) -> None:
        """Clear the aggregation (bench A/B cells, tests)."""
        with self._lock:
            self._stacks.clear()
            self._role_samples.clear()
            self._samples = 0
            self._attributed = 0
            self._overflowed = 0
            self._wakeups = 0

    # -- sampling ------------------------------------------------------

    def _run(self) -> None:
        interval = 1.0 / self.config.hz
        own_ident = threading.get_ident()
        while not self._stop.wait(interval):
            try:
                self._sample_once(own_ident)
            except Exception:  # noqa: BLE001 — the sampler must survive
                logger.exception("profiler sample failed")

    def _sample_once(self, own_ident: int) -> None:
        # Thread names are resolved per wakeup: enumerate() is a lock
        # + list copy, frames a dict copy — both GIL-atomic enough
        # that a name can at worst be one wakeup stale.
        names = {
            thread.ident: thread.name
            for thread in threading.enumerate()
        }
        frames = sys._current_frames()
        folded: List[Tuple[str, bool]] = []
        try:
            for ident, frame in frames.items():
                if ident == own_ident:
                    continue
                name = names.get(ident, f"tid-{ident}")
                stack: List[str] = []
                depth = 0
                while frame is not None and depth < MAX_DEPTH:
                    stack.append(_frame_label(frame))
                    frame = frame.f_back
                    depth += 1
                stack.append(thread_role(name))
                stack.reverse()  # root first, leaf last
                folded.append((tuple(stack), is_attributed(name)))
        finally:
            del frames  # drop frame refs promptly (they pin locals)
        with self._lock:
            self._wakeups += 1
            for stack, attributed in folded:
                self._samples += 1
                if attributed:
                    self._attributed += 1
                role = stack[0]
                self._role_samples[role] = (
                    self._role_samples.get(role, 0) + 1
                )
                count = self._stacks.get(stack)
                if count is not None:
                    self._stacks[stack] = count + 1
                elif len(self._stacks) < self.config.max_stacks:
                    self._stacks[stack] = 1
                else:
                    self._overflowed += 1
                    bucket = (role, "<other>")
                    self._stacks[bucket] = (
                        self._stacks.get(bucket, 0) + 1
                    )

    # -- read surface --------------------------------------------------

    def _snapshot(self) -> Tuple[Dict[Tuple[str, ...], int], dict]:
        with self._lock:
            stacks = dict(self._stacks)
            meta = {
                "running": self.running(),
                "hz": self.config.hz,
                "samples": self._samples,
                "wakeups": self._wakeups,
                "attributed_samples": self._attributed,
                "attributed_fraction": (
                    round(self._attributed / self._samples, 4)
                    if self._samples
                    else 0.0
                ),
                "distinct_stacks": len(self._stacks),
                "max_stacks": self.config.max_stacks,
                "overflowed_samples": self._overflowed,
                "started_unix": self._started_at,
                "roles": dict(
                    sorted(
                        self._role_samples.items(),
                        key=lambda item: -item[1],
                    )
                ),
            }
        return stacks, meta

    def collapsed(self) -> str:
        """Collapsed/folded flamegraph format: one ``frame;frame N``
        line per distinct stack, root (the role) first."""
        stacks, _ = self._snapshot()
        lines = [
            f"{';'.join(stack)} {count}"
            for stack, count in sorted(stacks.items())
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def top(self, n: int = 30) -> List[dict]:
        """Top-N frames by SELF time (samples where the frame was the
        leaf), with the owning role split alongside."""
        stacks, meta = self._snapshot()
        total = meta["samples"] or 1
        selfs: Dict[Tuple[str, str], int] = {}
        for stack, count in stacks.items():
            key = (stack[0], stack[-1] if len(stack) > 1 else "<idle>")
            selfs[key] = selfs.get(key, 0) + count
        ranked = sorted(selfs.items(), key=lambda item: -item[1])[:n]
        return [
            {
                "role": role,
                "frame": frame,
                "self_samples": count,
                "self_pct": round(100.0 * count / total, 2),
            }
            for (role, frame), count in ranked
        ]

    def status(self, top: int = 30) -> dict:
        """The ``/debug/profile`` JSON payload."""
        _, meta = self._snapshot()
        meta["top"] = self.top(top)
        return meta


# Process-wide profiler, mirroring TRACER/METRICS: the service entry
# points start it (PROFILE_HZ=0 keeps it inert); embedders construct
# their own when they need isolated aggregation.
PROFILER = SamplingProfiler()
