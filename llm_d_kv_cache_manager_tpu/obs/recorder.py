"""Flight recorder: bounded in-memory retention of completed traces.

Three tiers of retention, all O(1)-bounded so the recorder can run
always-on in production:

* **ring** — the last ``ring_size`` completed traces, newest evicting
  oldest (the "what just happened" view);
* **slow reservoir** — the ``slow_keep`` slowest traces whose duration
  crossed ``slow_threshold_ms``, kept even after the ring has cycled
  past them (a min-heap: a new slow trace displaces the least-slow
  retained one).  This is the slow-threshold *promotion*: an
  interesting trace survives long after ordinary traffic has flushed
  the ring;
* **errored reservoir** — the last ``error_keep`` traces that finished
  with a non-ok status (poison-pill events, scoring exceptions,
  failed offload jobs).

``get`` resolves a trace id across all three tiers, so
``GET /debug/traces/<id>`` keeps working for a slow or errored trace
whose ring slot is long gone.
"""

from __future__ import annotations

import heapq
import threading
from collections import deque
from typing import Deque, List, Optional, Tuple

DEFAULT_RING_SIZE = 256
DEFAULT_SLOW_KEEP = 32
DEFAULT_ERROR_KEEP = 32
DEFAULT_SLOW_THRESHOLD_MS = 100.0


class FlightRecorder:
    def __init__(
        self,
        ring_size: int = DEFAULT_RING_SIZE,
        slow_keep: int = DEFAULT_SLOW_KEEP,
        error_keep: int = DEFAULT_ERROR_KEEP,
        slow_threshold_ms: float = DEFAULT_SLOW_THRESHOLD_MS,
    ) -> None:
        if ring_size <= 0:
            raise ValueError("ring_size must be positive")
        if slow_keep <= 0 or error_keep <= 0:
            raise ValueError("reservoir sizes must be positive")
        self.ring_size = ring_size
        self.slow_keep = slow_keep
        self.error_keep = error_keep
        self.slow_threshold_ms = slow_threshold_ms
        self._lock = threading.Lock()
        self._ring: Deque = deque(maxlen=ring_size)  # guarded-by: _lock
        # Min-heap of (duration_s, seq, trace): the root is the least
        # slow retained trace, displaced first.  seq breaks duration
        # ties so traces never compare.
        self._slow: List[Tuple[float, int, object]] = []  # guarded-by: _lock
        self._errored: Deque = deque(maxlen=error_keep)  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._recorded = 0  # guarded-by: _lock
        self._slow_promoted = 0  # guarded-by: _lock
        self._error_recorded = 0  # guarded-by: _lock

    def record(self, trace) -> None:
        """Retain a finished trace (called exactly once, by finish())."""
        duration_ms = (trace.duration_s or 0.0) * 1000.0
        with self._lock:
            self._seq += 1
            self._recorded += 1
            self._ring.append(trace)
            if trace.status != "ok":
                self._error_recorded += 1
                self._errored.append(trace)
            if duration_ms >= self.slow_threshold_ms:
                self._slow_promoted += 1
                heapq.heappush(
                    self._slow, (trace.duration_s, self._seq, trace)
                )
                if len(self._slow) > self.slow_keep:
                    heapq.heappop(self._slow)

    def get(self, trace_id: str) -> Optional[object]:
        """Resolve a trace id across ring + slow + errored tiers."""
        with self._lock:
            for trace in reversed(self._ring):
                if trace.trace_id == trace_id:
                    return trace
            for _, _, trace in self._slow:
                if trace.trace_id == trace_id:
                    return trace
            for trace in reversed(self._errored):
                if trace.trace_id == trace_id:
                    return trace
        return None

    def recent(self, limit: int = 50) -> List[object]:
        """Newest-first slice of the ring."""
        with self._lock:
            return list(self._ring)[::-1][:limit]

    def slow(self, limit: int = 50) -> List[object]:
        """Slowest-first slice of the slow reservoir."""
        with self._lock:
            ordered = sorted(self._slow, key=lambda item: -item[0])
        return [trace for _, _, trace in ordered[:limit]]

    def errored(self, limit: int = 50) -> List[object]:
        """Newest-first slice of the errored reservoir."""
        with self._lock:
            return list(self._errored)[::-1][:limit]

    def stats(self) -> dict:
        """Occupancy and throughput counters for /healthz."""
        with self._lock:
            return {
                "ring_size": self.ring_size,
                "ring_occupancy": len(self._ring),
                "slow_retained": len(self._slow),
                "errored_retained": len(self._errored),
                "recorded": self._recorded,
                "slow_promoted": self._slow_promoted,
                "errors_recorded": self._error_recorded,
                "slow_threshold_ms": self.slow_threshold_ms,
            }

    def clear(self) -> None:
        """Drop all retained traces and counters (test isolation)."""
        with self._lock:
            self._ring.clear()
            self._slow.clear()
            self._errored.clear()
            self._seq = 0
            self._recorded = 0
            self._slow_promoted = 0
            self._error_recorded = 0
