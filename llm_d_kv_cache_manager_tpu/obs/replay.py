"""Replay-to-divergence harness for capture artifacts.

Re-drives a capture (``obs/capture.py`` wire format) through a FRESH
in-process stack — the real ``kvevents.Pool`` write path and the real
``Indexer`` read path, over either a single in-memory index or a
3-replica :class:`~..cluster.harness.LocalCluster` — and reports the
first divergence between replayed and recorded outputs:

* **scores** — every recorded scoring request is re-issued in the
  recorded global order (the capture's single ingress seq) and must
  reproduce the recorded score map bit-identically;
* **seq classifications** — the recorded per-(pod, topic) sequence
  stream is re-fed through the real ``TopicSeqTracker`` and each
  message's gap classification must match what the live subscriber
  recorded (a mutated or torn capture shows up here first);
* **final index state** — when the artifact carries a state section
  and no capture ring was truncated, the replayed index's
  canonicalized ``dump_entries`` must equal the recorded one.

Determinism ground rules the harness enforces on itself:

* The replayed token streams ARE the recorded ones: prompts are
  re-rendered from the recorded token chains through a word-per-token
  tokenizer, and the replay stack pins
  ``min_prefix_overlap_ratio > 1`` so the prefix-store fast path can
  never re-truncate a stream the live store already truncated.
* Event records replay strictly before any later score record: the
  pool is drained at every event→score boundary, so replayed reads
  see exactly the writes the recorded order said they saw.
* A message the live pool admitted and LATER displaced (two records:
  admitted, then shed) is cancelled up front — it never contributed
  to live state, so it must not contribute to replayed state.
* The capture header's config fingerprint must match this process
  (same knobs → same hash chains); mismatches raise
  :class:`CaptureMismatchError` naming the differing knobs instead of
  diverging mysteriously (``allow_mismatch=True`` overrides for
  forensic runs).

Turning an anomaly into a fixture (docs/observability.md "Incident
response runbook"): fetch the bundle's ``capture.cbor``, then

    from llm_d_kv_cache_manager_tpu.obs.replay import (
        load_capture, replay_capture,
    )
    report = replay_capture(load_capture(path))
    assert report.ok, report.divergence

``hack/replay_smoke.py`` is the CI-gated end-to-end version.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.obs.capture import (
    canonical_state,
    capture_enabled_env,  # noqa: F401  (re-export: wiring convenience)
    decode_f64,
    diff_knobs,
    load_artifact,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("obs.replay")

DEFAULT_CLUSTER_REPLICAS = 3


class CaptureMismatchError(ValueError):
    """The capture was recorded under different config knobs than this
    process resolves — replaying would diverge for config reasons, not
    behavior reasons.  ``differences`` names each mismatched knob."""

    def __init__(
        self,
        fingerprint: str,
        differences: List[str],
        source: Optional[str] = None,
    ) -> None:
        self.fingerprint = fingerprint
        self.differences = differences
        self.source = source
        detail = "; ".join(differences) or "package version differs"
        # Forensics over a directory of bundles needs the offending
        # artifact named IN the message (the short fingerprint is what
        # `kvtpu_build_info` and manifests print).
        artifact = f"{source} " if source else ""
        super().__init__(
            f"capture {artifact}(fingerprint {fingerprint[:8]}, full "
            f"{fingerprint}) does not match this process ({detail}); "
            "set the knobs to the recorded values or pass "
            "allow_mismatch=True"
        )


def load_capture(
    source, allow_mismatch: bool = False
) -> dict:
    """Load + validate a capture artifact from a path or raw bytes.

    Refuses (``CaptureMismatchError``) when the recorded config
    fingerprint differs from this process's unless ``allow_mismatch``.
    """
    if isinstance(source, (bytes, bytearray, memoryview)):
        data = bytes(source)
        source_name = None
    else:
        source_name = str(source)
        with open(source, "rb") as handle:
            data = handle.read()
    capture = load_artifact(data)
    from llm_d_kv_cache_manager_tpu.obs.capture import config_fingerprint

    if capture["fingerprint"] != config_fingerprint():
        differences = diff_knobs(capture["knobs"])
        if not allow_mismatch:
            raise CaptureMismatchError(
                capture["fingerprint"], differences, source_name
            )
        logger.warning(
            "replaying a mismatched capture %s (%s): %s",
            source_name or "<bytes>",
            capture["fingerprint"],
            "; ".join(differences) or "version drift",
        )
    return capture


@dataclass
class ReplayReport:
    """Outcome of one replay; ``ok`` means zero divergence."""

    mode: str
    records: int = 0
    events_applied: int = 0
    events_shed: int = 0
    events_cancelled: int = 0
    scores_compared: int = 0
    classifications_checked: int = 0
    state_compared: bool = False
    truncated_sources: List[str] = field(default_factory=list)
    divergence: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return self.divergence is None

    def to_dict(self) -> dict:
        return {
            "mode": self.mode,
            "ok": self.ok,
            "records": self.records,
            "events_applied": self.events_applied,
            "events_shed": self.events_shed,
            "events_cancelled": self.events_cancelled,
            "scores_compared": self.scores_compared,
            "classifications_checked": self.classifications_checked,
            "state_compared": self.state_compared,
            "truncated_sources": self.truncated_sources,
            "divergence": self.divergence,
        }


class _ReplayTokenizer:
    """Word-per-token tokenizer over prompts rendered by
    :func:`render_prompt` — the inverse pair that feeds recorded token
    chains back through the REAL tokenize→hash→lookup→score path."""

    def type(self) -> str:
        return "capture-replay"

    def encode(self, prompt: str, model_name: str, add_special_tokens):
        from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
            Encoding,
        )

        tokens: List[int] = []
        offsets: List[Tuple[int, int]] = []
        pos = 0
        for word in prompt.split(" "):
            if word.startswith("t"):
                tokens.append(int(word[1:]))
                offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def render_prompt(tokens) -> str:
    """The prompt text whose :class:`_ReplayTokenizer` encoding is
    exactly ``tokens``."""
    return " ".join(f"t{int(token)}" for token in tokens)


def _cancel_displaced(records: List[list]) -> Tuple[Dict[int, bool], int]:
    """Map record seq -> cancelled for kvevents records: an admitted
    message later re-recorded as shed (cross-batch displacement) never
    reached the live index, so its admitted record must not replay."""
    cancelled: Dict[int, bool] = {}
    open_admits: Dict[tuple, List[int]] = {}
    n_cancelled = 0
    for record in records:
        if record[0] != 0:
            continue
        seq = record[1]
        key = (record[3], record[4], record[6])  # pod, topic, msg seq
        if record[9] == "admitted":
            open_admits.setdefault(key, []).append(seq)
        elif record[8] is None:
            # A shed record without a payload is the displacement
            # notice for a previously admitted message (shed-at-admit
            # records carry their payload).
            pending = open_admits.get(key)
            if pending:
                cancelled[pending.pop(0)] = True
                n_cancelled += 1
    return cancelled, n_cancelled


def replay_capture(
    capture: dict,
    mode: str = "single",
    replicas: int = DEFAULT_CLUSTER_REPLICAS,
    pool_concurrency: int = 2,
) -> ReplayReport:
    """Re-drive a loaded capture through a fresh stack; see module
    docstring.  ``mode`` is ``"single"`` (in-memory index) or
    ``"cluster"`` (``LocalCluster`` with ``replicas`` real replicas
    behind the ``RemoteIndex``)."""
    if mode not in ("single", "cluster"):
        raise ValueError(f"unknown replay mode: {mode!r}")
    from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
        Indexer,
        IndexerConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.pool import (
        Message,
        Pool,
        PoolConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
        TopicSeqTracker,
    )
    from llm_d_kv_cache_manager_tpu.tokenization.pool import (
        TokenizationPoolConfig,
    )

    meta = capture.get("meta") or {}
    block_size = int(meta.get("block_size", 16) or 16)
    hash_seed = str(meta.get("hash_seed", ""))
    report = ReplayReport(
        mode=mode,
        records=len(capture["records"]),
        truncated_sources=list(capture.get("truncated") or []),
    )

    cluster = None
    kv_block_index = None
    if mode == "cluster":
        from llm_d_kv_cache_manager_tpu.cluster import LocalCluster

        cluster = LocalCluster(
            [f"replay-{i}" for i in range(max(1, replicas))]
        )
        kv_block_index = cluster.remote_index
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=block_size, hash_seed=hash_seed
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                # The live capture already holds the SERVED token
                # streams (prefix-store truncation included); the
                # replay store must never re-truncate them, so the
                # fast path is pinned unreachable.
                min_prefix_overlap_ratio=1.1,
            ),
            cache_stats=False,
        ),
        tokenizer=_ReplayTokenizer(),
        kv_block_index=kv_block_index,
    )
    indexer.run()
    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        # The replay pool must NEVER shed: flow control dropping a
        # faithfully-recorded admitted message would read as a false
        # divergence.  Depth is effectively unbounded (the capture is
        # already fully in memory) and the periodic drain below keeps
        # the standing backlog small anyway.
        PoolConfig(
            concurrency=max(1, pool_concurrency),
            max_queue_depth=1 << 30,
        ),
    )
    pool.start()
    trackers: Dict[str, TopicSeqTracker] = {}
    cancelled, report.events_cancelled = _cancel_displaced(
        capture["records"]
    )
    try:
        pending_drain = False
        for record in capture["records"]:
            if report.divergence is not None:
                break
            if record[0] == 0:
                (
                    _kind,
                    seq,
                    _ts,
                    pod,
                    topic,
                    model,
                    msg_seq,
                    seq_gap,
                    payload,
                    disposition,
                ) = record
                if disposition != "admitted" and payload is None:
                    # Displacement notice — already reconciled.
                    continue
                tracker = trackers.get(pod)
                if tracker is None:
                    tracker = trackers[pod] = TopicSeqTracker()
                observed = tracker.observe(str(topic), int(msg_seq))
                report.classifications_checked += 1
                if int(observed.gap) != int(seq_gap):
                    report.divergence = {
                        "at_seq": seq,
                        "source": "kvevents",
                        "kind": "seq_classification",
                        "detail": (
                            f"pod {pod} topic {topic} seq {msg_seq}: "
                            f"recorded gap {seq_gap}, replay computed "
                            f"{observed.gap}"
                        ),
                    }
                    break
                if disposition != "admitted":
                    report.events_shed += 1
                    continue
                if cancelled.pop(seq, False):
                    continue
                pool.add_task(
                    Message(
                        topic=str(topic),
                        payload=bytes(payload),
                        pod_identifier=str(pod),
                        model_name=str(model),
                        seq=int(msg_seq),
                    )
                )
                report.events_applied += 1
                pending_drain = True
                if report.events_applied % 4096 == 0:
                    # Long event-only stretches: keep the replayed
                    # backlog bounded without waiting for the next
                    # score record.
                    pool.drain()
                    pending_drain = False
            else:
                _kind, seq, _ts, model, tokens, pods, raw_scores = record
                if pending_drain:
                    pool.drain()
                    pending_drain = False
                want = {
                    str(pod): decode_f64(value)
                    for pod, value in raw_scores
                }
                got = indexer.get_pod_scores(
                    render_prompt(tokens),
                    str(model),
                    [str(p) for p in pods] if pods is not None else None,
                )
                report.scores_compared += 1
                if got != want:
                    report.divergence = {
                        "at_seq": seq,
                        "source": "scores",
                        "kind": "score",
                        "detail": _score_diff_detail(want, got),
                    }
                    break
        if report.divergence is None:
            pool.drain()
            recorded_state = capture.get("state")
            if recorded_state is not None and not report.truncated_sources:
                replayed = canonical_state(indexer.kv_block_index)
                report.state_compared = True
                if replayed != recorded_state:
                    report.divergence = {
                        "at_seq": None,
                        "source": "state",
                        "kind": "state",
                        "detail": _state_diff_detail(
                            recorded_state, replayed
                        ),
                    }
    finally:
        pool.shutdown()
        indexer.shutdown()
        if cluster is not None:
            cluster.close()
    return report


def _score_diff_detail(want: dict, got: dict) -> str:
    for pod in sorted(set(want) | set(got)):
        recorded = want.get(pod)
        replayed = got.get(pod)
        if recorded != replayed:
            return (
                f"pod {pod}: recorded {recorded!r}, replayed "
                f"{replayed!r} ({len(want)} recorded / {len(got)} "
                "replayed pods)"
            )
    return "score maps differ"


def _state_diff_detail(recorded: list, replayed: list) -> str:
    rec_blocks = {key: pods for key, pods in recorded[0]}
    rep_blocks = {key: pods for key, pods in replayed[0]}
    for key in sorted(set(rec_blocks) | set(rep_blocks)):
        if rec_blocks.get(key) != rep_blocks.get(key):
            return (
                f"request key {key:#x}: recorded "
                f"{rec_blocks.get(key)!r}, replayed "
                f"{rep_blocks.get(key)!r}"
            )
    rec_map = {ek: rk for ek, rk in recorded[1]}
    rep_map = {ek: rk for ek, rk in replayed[1]}
    for key in sorted(set(rec_map) | set(rep_map)):
        if rec_map.get(key) != rep_map.get(key):
            return (
                f"engine key {key:#x}: recorded mapping "
                f"{rec_map.get(key)!r}, replayed {rep_map.get(key)!r}"
            )
    return "index states differ"
