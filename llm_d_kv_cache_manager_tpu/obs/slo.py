"""SLO engine: declarative SLIs, burn rates, degradation envelopes.

The observability stack already answers "what happened" (metrics) and
"why was THIS request slow" (traces); this module answers the contract
question operators and chaos tests actually ask: *is the service
inside its declared envelope right now, and how fast is it burning
budget?*  One engine, three pieces:

* **SLI specs** (:class:`SloSpec`) are declarative: a name, a kind
  (``ratio`` — good/total cumulative counters, higher is better;
  ``gauge`` — an instantaneous value, lower is better; ``rate`` — a
  cumulative counter whose windowed delta is bounded), an
  ``objective`` (the healthy bound) and a ``degraded_bound`` (the
  outer envelope).  Sources are zero-arg callables over EXISTING
  surfaces — prometheus counters/histograms, the analytics ledger,
  cluster membership — so the engine adds no instrumentation of its
  own to hot paths.
* **Multi-window evaluation**: every SLI is sampled into a bounded
  time-series ring and evaluated over a fast and a slow window
  (``SLO_WINDOW_FAST_S`` / ``SLO_WINDOW_SLOW_S``).  Ratio SLIs report
  burn rates (bad-fraction / error-budget — 1.0 burns exactly the
  budget); a breach on EITHER window degrades, so a slow bleed and a
  fast spike both surface.
* **Degradation envelopes** are machine-readable state:
  ``healthy`` (inside objective), ``degraded`` (objective breached
  but inside ``degraded_bound`` — "degraded-with-bound"), or
  ``violated`` (outside the declared envelope).  ``GET /debug/slo``
  publishes the full payload, ``/healthz`` a compact block, and chaos
  cells (bench ``replica_scaleout``, ``hack/slo_smoke.py``) assert
  against the published envelope via :func:`envelope_violations`
  instead of re-inventing ad-hoc numeric pins.

Nothing here is cluster-specific: :func:`default_fleet_slos` wires
the fleet SLIs (score latency, event-plane shed + backlog, hit rate,
replica deaths, replication lag, failover rate) from whatever
surfaces the embedding application actually has.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS, safe_label
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("obs.slo")

STATE_HEALTHY = "healthy"
STATE_DEGRADED = "degraded"
STATE_VIOLATED = "violated"
_STATE_RANK = {STATE_HEALTHY: 0, STATE_DEGRADED: 1, STATE_VIOLATED: 2}

DEFAULT_WINDOW_FAST_S = 300.0
DEFAULT_WINDOW_SLOW_S = 3600.0

# Leaf lock: sampling/evaluation state only — sources are called
# OUTSIDE it (a source may take its own locks, e.g. the ledger's).
# kvlint: lock-order: SloEngine._lock ascending
lockorder.declare_ascending("SloEngine._lock")


@dataclass
class SloSpec:
    """One declarative SLI.

    ``ratio``: the source returns cumulative ``(good, total)`` counts;
    the windowed good-fraction must stay >= ``objective`` (healthy)
    and >= ``degraded_bound`` (the violation floor).

    ``gauge``: the source returns an instantaneous value; the windowed
    aggregate (``gauge_agg``: ``max`` or ``last``) must stay <=
    ``objective`` / <= ``degraded_bound``.

    ``rate``: the source returns ONE cumulative count; the fast-window
    delta must stay <= ``objective`` / <= ``degraded_bound``.
    """

    name: str
    kind: str = "ratio"
    objective: float = 0.99
    degraded_bound: float = 0.9
    description: str = ""
    gauge_agg: str = "max"
    unit: str = ""

    def validate(self) -> None:
        if self.kind not in ("ratio", "gauge", "rate"):
            raise ValueError(f"unknown SLI kind: {self.kind!r}")
        if self.kind == "ratio":
            if not (0.0 <= self.degraded_bound <= self.objective <= 1.0):
                raise ValueError(
                    f"ratio SLI {self.name}: need 0 <= degraded_bound "
                    f"<= objective <= 1, got {self.degraded_bound} / "
                    f"{self.objective}"
                )
        else:
            if self.degraded_bound < self.objective:
                raise ValueError(
                    f"{self.kind} SLI {self.name}: degraded_bound "
                    f"{self.degraded_bound} must be >= objective "
                    f"{self.objective} (lower is better)"
                )
        if self.gauge_agg not in ("max", "last"):
            raise ValueError(f"unknown gauge_agg: {self.gauge_agg!r}")


@dataclass
class _Series:
    spec: SloSpec
    source: Callable[[], Optional[Tuple[float, float]]]
    # (unix_ts, a, b): ratio -> cumulative (good, total); gauge ->
    # (value, 0); rate -> cumulative (count, 0).  guarded-by: engine
    # lock.
    samples: Deque[Tuple[float, float, float]] = field(
        default_factory=deque
    )
    source_errors: int = 0


def _worst(states: List[str]) -> str:
    rank = max((_STATE_RANK[s] for s in states), default=0)
    for name, value in _STATE_RANK.items():
        if value == rank:
            return name
    return STATE_HEALTHY  # pragma: no cover - rank always resolves


class SloEngine:
    """Samples SLI sources and publishes degradation envelopes."""

    def __init__(
        self,
        window_fast_s: float = DEFAULT_WINDOW_FAST_S,
        window_slow_s: float = DEFAULT_WINDOW_SLOW_S,
    ) -> None:
        if window_fast_s <= 0 or window_slow_s < window_fast_s:
            raise ValueError(
                "need 0 < window_fast_s <= window_slow_s, got "
                f"{window_fast_s} / {window_slow_s}"
            )
        self.window_fast_s = window_fast_s
        self.window_slow_s = window_slow_s
        self._lock = lockorder.tracked(
            threading.Lock(), "SloEngine._lock"
        )
        self._series: Dict[str, _Series] = {}  # guarded-by: _lock
        self._evaluations = 0  # guarded-by: _lock
        self._last_payload: Optional[dict] = None  # guarded-by: _lock
        # Overall-state transition listeners (the incident bundler's
        # trigger, obs/capture.py): called OUTSIDE the lock with
        # (old_state, new_state, payload) on every overall-state
        # change; a raising listener is logged, never propagated.
        # Transitions are queued under the lock (atomically with the
        # state update) and drained FIFO by a single dispatcher at a
        # time, so concurrent evaluate() calls (the poll thread +
        # /debug/slo hits) can never deliver healthy→violated AFTER
        # the recovery that followed it — out-of-order delivery would
        # burn the incident bundler's rate limit on a stale violation.
        self._listeners: List[
            Callable[[str, str, dict], None]
        ] = []  # guarded-by: _lock
        self._last_state: Optional[str] = None  # guarded-by: _lock
        self._transitions: Deque[
            Tuple[str, str, dict]
        ] = deque()  # guarded-by: _lock
        self._dispatching = False  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- registration ---------------------------------------------------

    def register(
        self,
        spec: SloSpec,
        source: Callable[[], Optional[Tuple[float, float]]],
    ) -> None:
        """Add one SLI.  ``source`` is a zero-arg callable returning
        the kind-specific tuple (see :class:`SloSpec`) or ``None``
        when the underlying surface is unavailable; a raising source
        is counted and treated as None (an SLI must never take the
        health endpoint down)."""
        spec.validate()
        with self._lock:
            if spec.name in self._series:
                raise ValueError(f"duplicate SLI: {spec.name}")
            self._series[spec.name] = _Series(spec, source)

    def sli_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def add_listener(
        self, listener: Callable[[str, str, dict], None]
    ) -> None:
        """Subscribe to overall-state transitions.  ``listener(old,
        new, payload)`` runs on whichever thread evaluated (the
        background poll or a /debug/slo hit), outside the engine lock;
        the first evaluation compares against ``healthy`` so an engine
        that boots straight into ``violated`` still notifies."""
        with self._lock:
            self._listeners.append(listener)

    # -- sampling -------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> None:
        """Record one snapshot of every SLI source (sources run
        outside the engine lock)."""
        now = time.time() if now is None else now
        with self._lock:
            series = list(self._series.values())
        retain = self.window_slow_s * 1.25
        for entry in series:
            try:
                raw = entry.source()
            except Exception:  # noqa: BLE001 - one SLI never downs /slo
                logger.exception(
                    "SLI source %s failed", entry.spec.name
                )
                entry.source_errors += 1
                continue
            if raw is None:
                continue
            if entry.spec.kind == "ratio":
                good, total = raw
                point = (now, float(good), float(total))
            elif entry.spec.kind == "rate":
                value = raw[0] if isinstance(raw, tuple) else raw
                point = (now, float(value), 0.0)
            else:  # gauge
                value = raw[0] if isinstance(raw, tuple) else raw
                point = (now, float(value), 0.0)
            with self._lock:
                samples = entry.samples
                # Concurrent samplers (the background poll + /debug/slo
                # hits on server threads) stamp `now` before running
                # sources outside the lock, so appends can arrive out
                # of order; a non-monotonic deque breaks _baseline's
                # scan.  A point older than the newest retained one
                # adds no window information — drop it.
                if samples and point[0] <= samples[-1][0]:
                    continue
                samples.append(point)
                while samples and samples[0][0] < now - retain:
                    samples.popleft()

    # -- window math ----------------------------------------------------

    @staticmethod
    def _baseline(
        samples, now: float, window_s: float
    ) -> Optional[Tuple[float, float, float]]:
        """The newest sample at or before ``now - window_s`` (the
        delta baseline), or the oldest sample when the series is
        younger than the window — a short-lived engine still reports
        over the data it has."""
        if not samples:
            return None
        cutoff = now - window_s
        baseline = None
        for point in samples:
            if point[0] <= cutoff:
                baseline = point
            else:
                break
        return baseline if baseline is not None else samples[0]

    def _ratio_window(
        self, samples, now: float, window_s: float, objective: float
    ) -> Tuple[Optional[float], Optional[float]]:
        """(good_fraction, burn_rate) over the window; (None, None)
        when the window saw no traffic."""
        if len(samples) < 2:
            return None, None
        base = self._baseline(samples, now, window_s)
        last = samples[-1]
        if base is None or last[0] <= base[0]:
            return None, None
        d_good = last[1] - base[1]
        d_total = last[2] - base[2]
        if d_total <= 0:
            return None, None
        # Counter resets (process restart behind a shared registry)
        # would produce negative deltas; clamp to the sane range.
        frac = min(1.0, max(0.0, d_good / d_total))
        budget = 1.0 - objective
        if budget <= 0:
            # A 100% objective has no budget to burn: any badness is a
            # breach; None keeps the payload JSON-clean (no Infinity).
            burn = 0.0 if frac >= 1.0 else None
        else:
            burn = (1.0 - frac) / budget
        return frac, burn

    def _counter_window(
        self, samples, now: float, window_s: float
    ) -> Optional[float]:
        if len(samples) < 2:
            return None
        base = self._baseline(samples, now, window_s)
        last = samples[-1]
        if base is None or last[0] <= base[0]:
            return None
        return max(0.0, last[1] - base[1])

    def _gauge_window(
        self, samples, now: float, window_s: float, agg: str
    ) -> Optional[float]:
        if not samples:
            return None
        cutoff = now - window_s
        values = [v for ts, v, _ in samples if ts >= cutoff]
        if not values:
            values = [samples[-1][1]]
        return values[-1] if agg == "last" else max(values)

    # -- evaluation -----------------------------------------------------

    def _evaluate_sli(self, entry: _Series, now: float) -> dict:
        spec = entry.spec
        with self._lock:
            samples = list(entry.samples)
        out: dict = {
            "kind": spec.kind,
            "objective": spec.objective,
            "degraded_bound": spec.degraded_bound,
            "description": spec.description,
            "samples": len(samples),
        }
        if spec.unit:
            out["unit"] = spec.unit
        if spec.kind == "ratio":
            frac_fast, burn_fast = self._ratio_window(
                samples, now, self.window_fast_s, spec.objective
            )
            frac_slow, burn_slow = self._ratio_window(
                samples, now, self.window_slow_s, spec.objective
            )
            value = frac_fast if frac_fast is not None else frac_slow
            out.update(
                value=value,
                value_slow=frac_slow,
                burn_fast=burn_fast,
                burn_slow=burn_slow,
            )
            if value is None:
                out["state"] = STATE_HEALTHY
                out["no_data"] = True
            elif value < spec.degraded_bound:
                out["state"] = STATE_VIOLATED
            elif value < spec.objective or (
                frac_slow is not None and frac_slow < spec.objective
            ):
                out["state"] = STATE_DEGRADED
            else:
                out["state"] = STATE_HEALTHY
        elif spec.kind == "rate":
            value = self._counter_window(
                samples, now, self.window_fast_s
            )
            slow = self._counter_window(samples, now, self.window_slow_s)
            out.update(value=value, value_slow=slow)
            if value is not None and spec.objective > 0:
                out["burn_fast"] = value / spec.objective
            if value is None:
                out["state"] = STATE_HEALTHY
                out["no_data"] = True
            elif value > spec.degraded_bound:
                out["state"] = STATE_VIOLATED
            elif value > spec.objective:
                out["state"] = STATE_DEGRADED
            else:
                out["state"] = STATE_HEALTHY
        else:  # gauge
            value = self._gauge_window(
                samples, now, self.window_fast_s, spec.gauge_agg
            )
            slow = self._gauge_window(
                samples, now, self.window_slow_s, spec.gauge_agg
            )
            out.update(value=value, value_slow=slow)
            if value is not None and spec.objective > 0:
                out["burn_fast"] = value / spec.objective
            # Gauges are instantaneous conditions: the fast-window
            # aggregate decides state; the slow aggregate is context
            # (a spike an hour ago should not pin "degraded").
            if value is None:
                out["state"] = STATE_HEALTHY
                out["no_data"] = True
            elif value > spec.degraded_bound:
                out["state"] = STATE_VIOLATED
            elif value > spec.objective:
                out["state"] = STATE_DEGRADED
            else:
                out["state"] = STATE_HEALTHY
        return out

    def evaluate(self, now: Optional[float] = None) -> dict:
        """The degradation envelope: per-SLI state + the overall worst
        (the payload ``GET /debug/slo`` serves and chaos cells assert
        against).  Also publishes the ``kvtpu_slo_*`` gauges."""
        now = time.time() if now is None else now
        with self._lock:
            series = dict(self._series)
            self._evaluations += 1
            evaluations = self._evaluations
        slis = {
            name: self._evaluate_sli(entry, now)
            for name, entry in sorted(series.items())
        }
        overall = _worst([s["state"] for s in slis.values()])
        for name, view in slis.items():
            METRICS.slo_state.labels(sli=safe_label(name)).set(
                _STATE_RANK[view["state"]]
            )
            for window, key in (("fast", "burn_fast"), ("slow", "burn_slow")):
                burn = view.get(key)
                if burn is not None:
                    METRICS.slo_burn_rate.labels(
                        sli=safe_label(name), window=window
                    ).set(burn)
        METRICS.slo_state.labels(sli="overall").set(_STATE_RANK[overall])
        payload = {
            "state": overall,
            "evaluated_unix": now,
            "evaluations": evaluations,
            "windows": {
                "fast_s": self.window_fast_s,
                "slow_s": self.window_slow_s,
            },
            "slis": slis,
        }
        with self._lock:
            self._last_payload = payload
            previous = self._last_state or STATE_HEALTHY
            self._last_state = overall
            if previous != overall and self._listeners:
                self._transitions.append((previous, overall, payload))
            if self._transitions and not self._dispatching:
                self._dispatching = True
                drain = True
            else:
                drain = False
        if drain:
            self._drain_transitions()
        return payload

    def _drain_transitions(self) -> None:
        """Deliver queued state transitions FIFO, one dispatcher at a
        time (the ``_dispatching`` flag hands late arrivals to the
        thread already draining); listeners run with NO engine lock
        held — they may read ``last_payload()`` or trigger an
        incident bundle."""
        while True:
            with self._lock:
                if not self._transitions:
                    self._dispatching = False
                    return
                previous, overall, payload = self._transitions.popleft()
                listeners = list(self._listeners)
            for listener in listeners:
                try:
                    listener(previous, overall, payload)
                except Exception:  # noqa: BLE001 - never down /slo
                    logger.exception(
                        "SLO transition listener failed (%s -> %s)",
                        previous,
                        overall,
                    )

    # -- surfaces -------------------------------------------------------

    def status(self, now: Optional[float] = None) -> dict:
        """The /debug/slo payload: sample-then-evaluate, so the
        endpoint is truthful even between background polls."""
        self.sample(now)
        payload = self.evaluate(now)
        with self._lock:
            payload["source_errors"] = {
                name: entry.source_errors
                for name, entry in self._series.items()
                if entry.source_errors
            }
        return payload

    def last_payload(self) -> Optional[dict]:
        """The most recent full evaluation payload (None before the
        first) — what the incident bundler snapshots as ``slo.json``
        without re-sampling every source mid-incident."""
        with self._lock:
            return self._last_payload

    def healthz_block(self) -> dict:
        """Compact envelope for /healthz, served from the LAST
        evaluation (the background poll or a /debug/slo hit keeps it
        fresh; ``evaluated_unix`` exposes staleness) — a 1 Hz liveness
        probe must not re-sample every SLI source per hit.  Falls back
        to one full evaluation when none has run yet."""
        with self._lock:
            payload = self._last_payload
        if payload is None:
            payload = self.status()
        block = {
            "state": payload["state"],
            "evaluated_unix": payload["evaluated_unix"],
        }
        for state_name in (STATE_DEGRADED, STATE_VIOLATED):
            names = [
                name
                for name, view in payload["slis"].items()
                if view["state"] == state_name
            ]
            if names:
                block[state_name] = names
        no_data = [
            name
            for name, view in payload["slis"].items()
            if view.get("no_data")
        ]
        if no_data:
            block["no_data"] = no_data
        return block

    # -- lifecycle ------------------------------------------------------

    def start(self, poll_interval_s: float = 5.0) -> None:
        """Background sample+evaluate loop (idempotent; restartable
        after ``close``)."""
        if poll_interval_s <= 0:
            raise ValueError("poll interval must be positive")
        if self._thread is not None:
            return
        # A previous close() left the stop flag set; without clearing
        # it the new thread would exit on its first wait() and polling
        # would silently stay dead.
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(poll_interval_s):
                try:
                    self.sample()
                    self.evaluate()
                except Exception:  # noqa: BLE001 - the loop must survive
                    logger.exception("SLO evaluation round failed")

        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=run, name="kvtpu-slo-engine", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None


def envelope_violations(payload: dict) -> List[str]:
    """Internal-consistency check of a published envelope: every SLI
    whose state claims "within bound" must actually be within its
    declared bound, and a ``violated`` overall state is itself a
    violation.  Chaos cells assert ``envelope_violations(payload) ==
    []`` instead of pinning ad-hoc numbers — the declared bounds ARE
    the contract."""
    problems: List[str] = []
    if payload.get("state") == STATE_VIOLATED:
        problems.append("overall state is violated")
    for name, view in (payload.get("slis") or {}).items():
        state = view.get("state")
        value = view.get("value")
        if value is None:
            continue
        bound = view.get("degraded_bound")
        if state == STATE_VIOLATED:
            problems.append(
                f"{name}: {value} outside declared bound {bound}"
            )
            continue
        if view.get("kind") == "ratio":
            if value < bound:
                problems.append(
                    f"{name}: state {state} but value {value} below "
                    f"declared bound {bound}"
                )
        elif value > bound:
            problems.append(
                f"{name}: state {state} but value {value} above "
                f"declared bound {bound}"
            )
    return problems


def envelope_states(payload: dict) -> Dict[str, str]:
    """Compact ``{sli: state}`` map (plus ``"overall"``) from one
    evaluation payload — the per-checkpoint shape the what-if engine's
    A/B replay compares to find the first point of SLO divergence
    (obs/whatif.py; docs/observability.md "What-if engine")."""
    states = {"overall": str(payload.get("state", STATE_HEALTHY))}
    for name, view in (payload.get("slis") or {}).items():
        states[str(name)] = str(view.get("state", STATE_HEALTHY))
    return states


# ------------------------- source constructors -------------------------


def counter_label_total(counter, **labels) -> float:
    """Sum of a labeled counter's ``_total`` samples matching
    ``labels`` (subset match)."""
    total = 0.0
    for metric in counter.collect():
        for sample in metric.samples:
            if not sample.name.endswith("_total"):
                continue
            if all(
                sample.labels.get(k) == v for k, v in labels.items()
            ):
                total += sample.value
    return total


def labeled_gauge_sum(gauge) -> float:
    """Sum of a labeled gauge across all label sets (0.0 when none)."""
    total = 0.0
    for metric in gauge.collect():
        for sample in metric.samples:
            total += sample.value
    return total


def labeled_gauge_max(gauge) -> float:
    """Max of a labeled gauge across all label sets (0.0 when none)."""
    best = 0.0
    for metric in gauge.collect():
        for sample in metric.samples:
            best = max(best, sample.value)
    return best


def histogram_latency_source(
    histogram, threshold_s: float
) -> Callable[[], Optional[Tuple[float, float]]]:
    """Ratio source from a prometheus histogram: good = observations
    <= the largest FINITE bucket bound <= ``threshold_s``, total =
    all observations — the classic "fraction of requests under X ms"
    SLI, windowed by the engine's cumulative-delta math.

    The bucket rounds DOWN, never up: a threshold between bounds (or
    past every finite bound — the +Inf bucket equals total by
    definition) must undercount "good", because rounding up would let
    a service miss the declared objective by most of a bucket width —
    or by any amount at all, past the widest bucket — while the SLI
    reports 100% healthy, the exact blindness the engine exists to
    remove.  Align the threshold (``SLO_SCORE_LATENCY_MS``) to a
    bucket bound for an exact reading.
    """

    def source() -> Optional[Tuple[float, float]]:
        good = None
        good_le = None
        total = 0.0
        for metric in histogram.collect():
            for sample in metric.samples:
                if sample.name.endswith("_bucket"):
                    try:
                        bound = float(sample.labels.get("le", ""))
                    except ValueError:
                        continue
                    if bound == float("inf"):
                        continue
                    if bound <= threshold_s and (
                        good_le is None or bound > good_le
                    ):
                        good_le = bound
                        good = sample.value
                elif sample.name.endswith("_count"):
                    total += sample.value
        if good is None:
            # Threshold below every finite bucket: nothing provably
            # under it — fully conservative.
            good = 0.0
        return good, total

    return source


def default_fleet_slos(
    window_fast_s: float = DEFAULT_WINDOW_FAST_S,
    window_slow_s: float = DEFAULT_WINDOW_SLOW_S,
    score_latency_s: float = 0.25,
    hit_rate_objective: float = 0.0,
    hit_rate_bound: Optional[float] = None,
    membership=None,
    pool=None,
) -> SloEngine:
    """The stock fleet SLO set, fed entirely from existing surfaces.

    ``membership`` (a ``cluster.ClusterMembership``) enables the
    replica-death and failover SLIs; ``pool`` (a ``kvevents.Pool``)
    enables the apply-side shed ratio.  A ``hit_rate_objective`` of 0
    keeps the hit-rate SLI informational (always healthy) — hit rate
    is workload-dependent, so the floor is deliberately opt-in
    (``SLO_HIT_RATE_OBJECTIVE``)."""
    from llm_d_kv_cache_manager_tpu.metrics.collector import (
        counter_total,
    )

    engine = SloEngine(window_fast_s, window_slow_s)
    engine.register(
        SloSpec(
            "score_availability",
            kind="ratio",
            objective=0.999,
            degraded_bound=0.99,
            description="fraction of scored requests answering 200",
        ),
        lambda: (
            counter_label_total(METRICS.score_requests, outcome="ok"),
            counter_total(METRICS.score_requests),
        ),
    )
    engine.register(
        SloSpec(
            "score_latency",
            kind="ratio",
            objective=0.99,
            degraded_bound=0.90,
            description=(
                f"fraction of scored requests under {score_latency_s}s"
            ),
        ),
        histogram_latency_source(METRICS.score_latency, score_latency_s),
    )
    engine.register(
        SloSpec(
            "hit_rate",
            kind="ratio",
            objective=hit_rate_objective,
            degraded_bound=(
                hit_rate_bound
                if hit_rate_bound is not None
                else hit_rate_objective / 2.0
            ),
            description="ledger hit fraction of scored requests",
        ),
        lambda: (
            counter_label_total(
                METRICS.cachestats_requests, outcome="hit"
            ),
            counter_total(METRICS.cachestats_requests),
        ),
    )
    engine.register(
        SloSpec(
            "event_apply_backlog",
            kind="gauge",
            objective=1024.0,
            degraded_bound=16384.0,
            description=(
                "queued-not-applied event messages across pod lanes"
            ),
            unit="messages",
        ),
        lambda: (labeled_gauge_sum(METRICS.kvevents_pod_backlog), 0.0),
    )
    engine.register(
        SloSpec(
            "resync_suspect_pods",
            kind="gauge",
            objective=0.0,
            degraded_bound=8.0,
            description="pods gapped and not yet resynced",
            unit="pods",
        ),
        lambda: (
            labeled_gauge_sum(METRICS.kvevents_suspect_pods),
            0.0,
        ),
    )
    if pool is not None:
        def shed_source() -> Optional[Tuple[float, float]]:
            applied = float(
                pool.stage_stats().get("apply_msgs", 0) or 0
            )
            dropped = counter_total(METRICS.kvevents_dropped)
            return applied, applied + dropped

        engine.register(
            SloSpec(
                "event_shed",
                kind="ratio",
                objective=0.99,
                degraded_bound=0.90,
                description=(
                    "fraction of event messages applied (not shed)"
                ),
            ),
            shed_source,
        )
    if membership is not None:
        engine.register(
            SloSpec(
                "replicas_dead",
                kind="gauge",
                objective=0.0,
                degraded_bound=1.0,
                description=(
                    "configured replicas currently out of the ring"
                ),
                unit="replicas",
            ),
            lambda: (
                float(
                    len(membership.members()) - len(membership.alive())
                ),
                0.0,
            ),
        )
        engine.register(
            SloSpec(
                "failovers",
                kind="rate",
                objective=0.0,
                degraded_bound=2.0,
                description="ring removals in the fast window",
                unit="failovers",
            ),
            lambda: (float(membership.failover_count()), 0.0),
        )
        engine.register(
            SloSpec(
                "replication_lag",
                kind="gauge",
                objective=512.0,
                degraded_bound=8192.0,
                description=(
                    "max journal records a replication follower is "
                    "behind its primary"
                ),
                unit="records",
            ),
            lambda: (labeled_gauge_max(METRICS.cluster_replica_lag), 0.0),
        )
    return engine
