"""Ring-buffer gauge timelines (docs/observability.md "Gauge
timelines").

The flight recorder answers "what did request X do", the SLO engine
answers "are we degraded NOW" — this module answers "what did the
minutes BEFORE the burn-rate alert look like": a background sampler
thread polls a set of registered zero-arg sources once a second and
keeps each series' last ``window_s`` seconds in a bounded ring, read
back at ``GET /debug/timeline``.

Sources are plain callables returning a float (gauge semantics; feed
``counter_total`` wrappers for monotonic series — the reader can
difference them).  A source that raises records ``None`` for that
slot and keeps sampling: one broken gauge must never blind the rest
of the timeline.  Memory is bounded by construction:
``series x window_s`` points, no per-sample allocation beyond the
ring slot.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("obs.timeline")

DEFAULT_WINDOW_S = 900
RESOLUTION_S = 1.0
MAX_SERIES = 64


def _env_window() -> int:
    raw = os.environ.get("TIMELINE_WINDOW_S", "")
    if not raw:
        return DEFAULT_WINDOW_S
    try:
        return max(0, int(raw))
    except ValueError:
        logger.warning(
            "invalid TIMELINE_WINDOW_S=%r; using %s",
            raw,
            DEFAULT_WINDOW_S,
        )
        return DEFAULT_WINDOW_S


class _Series:
    __slots__ = ("name", "description", "source", "ring", "errors")

    def __init__(
        self,
        name: str,
        description: str,
        source: Callable[[], float],
        window: int,
    ) -> None:
        self.name = name
        self.description = description
        self.source = source
        self.ring: Deque[Tuple[float, Optional[float]]] = deque(
            maxlen=window
        )
        self.errors = 0


class GaugeTimeline:
    """1s-resolution bounded history over registered gauge sources."""

    def __init__(self, window_s: Optional[int] = None) -> None:
        self.window_s = _env_window() if window_s is None else window_s
        self._lock = lockorder.tracked(
            threading.Lock(), "GaugeTimeline._lock"
        )
        self._series: Dict[str, _Series] = {}  # guarded-by: _lock
        self._ticks = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(
        self,
        name: str,
        source: Callable[[], float],
        description: str = "",
    ) -> bool:
        """Add a series (idempotent by name); False past MAX_SERIES."""
        with self._lock:
            if name in self._series:
                return True
            if len(self._series) >= MAX_SERIES:
                logger.warning(
                    "timeline series cap (%d) reached; dropping %r",
                    MAX_SERIES,
                    name,
                )
                return False
            self._series[name] = _Series(
                name, description, source, max(1, self.window_s)
            )
            return True

    # -- lifecycle -----------------------------------------------------

    def start(self) -> bool:
        """Spawn the 1s sampler; no-op (False) when window_s is 0."""
        if self.window_s <= 0:
            return False
        if self._thread is not None and self._thread.is_alive():
            return True
        self._stop.clear()
        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=self._run, name="kvtpu-timeline", daemon=True
        )
        self._thread.start()
        return True

    def close(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None

    def running(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    # -- sampling ------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(RESOLUTION_S):
            self.sample_once()

    def sample_once(self, now: Optional[float] = None) -> None:
        """One sampling pass (the loop body; tests drive it directly).

        Sources run OUTSIDE the timeline lock: they reach into pool /
        cluster / metrics internals that take their own locks, and
        nesting those under ours is a KV006 hazard for zero benefit.
        """
        stamp = time.time() if now is None else now
        with self._lock:
            series = list(self._series.values())
        readings: List[Tuple[_Series, Optional[float]]] = []
        for entry in series:
            try:
                readings.append((entry, float(entry.source())))
            except Exception:  # noqa: BLE001 — one bad gauge, not all
                entry.errors += 1
                if entry.errors == 1:
                    logger.exception(
                        "timeline source %r failed (logged once)",
                        entry.name,
                    )
                readings.append((entry, None))
        with self._lock:
            self._ticks += 1
            for entry, value in readings:
                entry.ring.append((stamp, value))

    # -- read surface --------------------------------------------------

    def snapshot(
        self,
        last_s: Optional[float] = None,
        series: Optional[str] = None,
    ) -> dict:
        """The ``/debug/timeline`` payload: per-series point arrays
        (``[unix_seconds, value|null]``), newest last, optionally
        bounded to the trailing ``last_s`` seconds or one series."""
        cutoff = None if last_s is None else time.time() - last_s
        with self._lock:
            if series is None:
                names = sorted(self._series)
            elif series in self._series:
                names = [series]
            else:
                # An unknown name returns an EMPTY map, never the
                # full payload: a typo'd ?series= filter that
                # silently hands back every series is undetectable
                # from the response shape.
                names = []
            out_series = {}
            for name in names:
                entry = self._series[name]
                points = [
                    [ts, value]
                    for ts, value in entry.ring
                    if cutoff is None or ts >= cutoff
                ]
                out_series[name] = {
                    "description": entry.description,
                    "errors": entry.errors,
                    "points": points,
                }
            return {
                "resolution_s": RESOLUTION_S,
                "window_s": self.window_s,
                "ticks": self._ticks,
                "running": self.running(),
                "series": out_series,
            }


def register_default_series(
    timeline: GaugeTimeline,
    pool=None,
    remote_index=None,
    resync=None,
) -> None:
    """Wire the stock fleet series (api/http_service.py): shard
    backlog + per-pod lanes, staging lane waits, cluster RPC
    in-flight, suspect pods, score traffic, and the process runtime
    block — the gauges an operator walks back from a burn-rate alert.
    """
    from llm_d_kv_cache_manager_tpu.metrics.collector import (
        METRICS,
        counter_total,
        gauge_total,
        gauge_value,
        update_process_metrics,
    )

    timeline.register(
        "score_requests_total",
        lambda: counter_total(METRICS.score_requests),
        "scored requests served (monotonic; difference for rate)",
    )
    if pool is not None:
        # The pool's own shard walk, not the per-pod backlog gauge
        # sum: the gauge cache is bounded and label-sanitized, the
        # walk is exact.  Both series share ONE walk per tick —
        # memoized briefly so the 1s sampler takes each shard lock
        # once, not once per series (the sampler is the only caller,
        # so the plain-dict memo needs no lock).
        lane_memo = {"stamp": -1.0, "value": (0, 0)}

        def _pool_lane_stats() -> tuple:
            now = time.monotonic()
            if now - lane_memo["stamp"] > 0.5:
                lane_memo["value"] = pool.lane_stats()
                lane_memo["stamp"] = now
            return lane_memo["value"]

        timeline.register(
            "event_backlog",
            lambda: float(_pool_lane_stats()[0]),
            "queued-not-applied event messages across all pod lanes",
        )
        timeline.register(
            "event_lanes",
            lambda: float(_pool_lane_stats()[1]),
            "pods holding a live (non-empty) event lane",
        )
    else:
        timeline.register(
            "event_backlog",
            lambda: gauge_total(METRICS.kvevents_pod_backlog),
            "queued-not-applied event messages across all pod lanes",
        )
    timeline.register(
        "events_dropped_total",
        lambda: counter_total(METRICS.kvevents_dropped),
        "shed event messages (monotonic)",
    )
    timeline.register(
        "suspect_pods",
        lambda: gauge_value(METRICS.kvevents_suspect_pods),
        "pods gapped and not yet resynced",
    )
    timeline.register(
        "poller_sockets",
        lambda: gauge_total(METRICS.kvevents_poller_sockets),
        "SUB sockets multiplexed across event-plane pollers",
    )
    timeline.register(
        "staging_lane_waits_total",
        lambda: counter_total(METRICS.offload_staging_lane_waits),
        "staged transfers that waited for a staging lane (monotonic)",
    )
    timeline.register(
        "lock_contention_total",
        lambda: counter_total(METRICS.lock_contention),
        "contended sampled lock acquires (monotonic; "
        "LOCK_CONTENTION_SAMPLE gates)",
    )
    timeline.register(
        "process_rss_bytes",
        lambda: update_process_metrics()["rss_bytes"],
        "resident set size",
    )
    timeline.register(
        "process_threads",
        lambda: float(threading.active_count()),
        "live Python threads",
    )
    if remote_index is not None:
        timeline.register(
            "cluster_rpc_in_flight",
            lambda: float(remote_index.in_flight()),
            "router->replica RPCs currently outstanding",
        )
    if resync is not None:
        timeline.register(
            "resyncs_total",
            lambda: float(
                counter_total(METRICS.kvevents_resyncs)
            ),
            "anti-entropy pod resyncs (monotonic)",
        )
