"""Request-scoped tracing: spans, sampling, W3C traceparent, propagation.

Zero-hard-dependency span tracing for the scoring read path, the
KV-event write path, and the offload pipelines.  Design constraints
(ISSUE 3):

* **Always-on cheap.**  The untraced path allocates nothing: ``span()``
  returns a preallocated null context manager when no trace is active,
  and an unsampled ``start_trace`` costs one counter increment.
* **Explicit propagation.**  A ``contextvars.ContextVar`` carries the
  active trace within a thread; crossing the thread-pool boundaries we
  own (tokenization pool, kvevents shards, offload workers) is done by
  attaching the ``Trace`` object to the queued task and re-entering it
  with ``use_trace`` on the worker — never by thread-locals that would
  silently fail to cross.
* **Thread-safe traces.**  Spans complete from worker threads while the
  submitting thread keeps tracing, so span append is locked.
* **Flat span model.**  Spans carry an optional ``parent`` stage *name*
  rather than a span-id tree: top-level spans (``parent is None``) are
  the request's sequential stage breakdown — their durations sum to
  ~the end-to-end latency — and dotted children (``tokenize.encode``)
  attribute time inside a stage.  This is what /debug and ``explain=1``
  render, and what feeds ``kvtpu_stage_latency_seconds{stage=...}``.

Env knobs (read at import; ``configure`` overrides for tests/embeds):
``TRACE_SAMPLE_RATE`` (0..1, default 0.01), ``TRACE_RING_SIZE``
(default 256), ``TRACE_SLOW_MS`` (slow-promotion threshold, default
100).  A request bearing a ``traceparent`` header with the sampled
flag set is always traced regardless of the rate — that is the
operator's "trace THIS request" switch.
"""

from __future__ import annotations

import contextvars
import os
import random
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, NamedTuple, Optional

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.obs.recorder import (
    DEFAULT_ERROR_KEEP,
    DEFAULT_RING_SIZE,
    DEFAULT_SLOW_KEEP,
    DEFAULT_SLOW_THRESHOLD_MS,
    FlightRecorder,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("obs.trace")

DEFAULT_SAMPLE_RATE = 0.01

_ZERO_TRACE_ID = "0" * 32
_ZERO_SPAN_ID = "0" * 16

# version-trace_id-parent_id-flags; the trailing group captures any
# future-version suffix fields (W3C forward compatibility: parsers
# must accept higher versions by reading the first four fields and
# ignoring the rest; version 00 allows no suffix).
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})(-.*)?$"
)


class ParentContext(NamedTuple):
    """Parsed W3C traceparent header."""

    trace_id: str
    span_id: str
    sampled: bool


def parse_traceparent(header: Optional[str]) -> Optional[ParentContext]:
    """Parse a W3C traceparent header; None when absent or malformed."""
    if not header:
        return None
    match = _TRACEPARENT_RE.match(header.strip().lower())
    if not match:
        return None
    version, trace_id, span_id, flags, suffix = match.groups()
    # "ff" is forbidden by the spec; all-zero ids are invalid; only
    # future versions may carry suffix fields.
    if version == "ff" or (version == "00" and suffix):
        return None
    if trace_id == _ZERO_TRACE_ID or span_id == _ZERO_SPAN_ID:
        return None
    return ParentContext(trace_id, span_id, bool(int(flags, 16) & 0x01))


def format_traceparent(
    trace_id: str, span_id: str, sampled: bool = True
) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def _new_trace_id() -> str:
    while True:
        trace_id = f"{random.getrandbits(128):032x}"
        if trace_id != _ZERO_TRACE_ID:
            return trace_id


def _new_span_id() -> str:
    while True:
        span_id = f"{random.getrandbits(64):016x}"
        if span_id != _ZERO_SPAN_ID:
            return span_id


class Span:
    """One timed stage of a trace (append-to-trace happens at exit)."""

    __slots__ = ("name", "parent", "start", "end", "status", "attrs")

    def __init__(
        self, name: str, parent: Optional[str], start: float
    ) -> None:
        self.name = name
        self.parent = parent
        self.start = start
        self.end = start
        self.status = "ok"
        self.attrs: Dict[str, Any] = {}

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    @property
    def duration_s(self) -> float:
        return self.end - self.start


class _SpanCtx:
    """Context manager recording one span onto a trace."""

    __slots__ = ("_trace", "_span")

    def __init__(self, trace: "Trace", span: Span) -> None:
        self._trace = trace
        self._span = span

    def __enter__(self) -> Span:
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._span.end = time.perf_counter()
        if exc_type is not None:
            self._span.status = "error"
            self._span.attrs["error"] = repr(exc)
        self._trace.append_span(self._span)
        return False


class _NullSpan:
    """Inert span stand-in: attribute writes vanish."""

    __slots__ = ()

    def set_attr(self, key: str, value: Any) -> None:
        return None


class _NullSpanCtx:
    """Stateless, shareable no-op span context (untraced path)."""

    __slots__ = ()

    def __enter__(self) -> _NullSpan:
        return _NULL_SPAN

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SPAN = _NullSpan()
_NULL_SPAN_CTX = _NullSpanCtx()


class Trace:
    """One sampled request: id, attributes, and completed spans."""

    def __init__(
        self,
        name: str,
        trace_id: str,
        root_span_id: str,
        recorder: FlightRecorder,
        parent_span_id: Optional[str] = None,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.root_span_id = root_span_id
        self.parent_span_id = parent_span_id
        self._recorder = recorder
        self.start_wall = time.time()
        self.start = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.status = "in_flight"
        self._lock = threading.Lock()
        self._spans: List[Span] = []  # guarded-by: _lock
        self._attrs: Dict[str, Any] = {}  # guarded-by: _lock
        self._error: Optional[str] = None  # guarded-by: _lock
        self._finished = False  # guarded-by: _lock

    # -- span recording (any thread) --

    def span(self, name: str, parent: Optional[str] = None) -> _SpanCtx:
        """Open a span; it records itself on context exit."""
        return _SpanCtx(self, Span(name, parent, time.perf_counter()))

    def add_completed(
        self,
        name: str,
        start: float,
        end: Optional[float] = None,
        parent: Optional[str] = None,
    ) -> Span:
        """Record an already-elapsed interval (queue waits, async I/O)
        from explicit ``time.perf_counter()`` stamps."""
        span = Span(name, parent, start)
        span.end = time.perf_counter() if end is None else end
        self.append_span(span)
        return span

    def append_span(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def set_attr(self, key: str, value: Any) -> None:
        with self._lock:
            self._attrs[key] = value

    def set_error(self, message: str) -> None:
        with self._lock:
            self._error = message

    # -- completion --

    def finish(self, status: Optional[str] = None) -> None:
        """Seal the trace and hand it to the flight recorder.

        Idempotent: only the first call records.  Status defaults to
        "error" when ``set_error`` was called, else "ok".
        """
        end = time.perf_counter()
        with self._lock:
            if self._finished:
                return
            self._finished = True
            self.duration_s = end - self.start
            if status is None:
                status = "ok" if self._error is None else "error"
            self.status = status
            spans = list(self._spans)
        # Outside the trace lock: the prometheus client and the
        # recorder take their own locks.
        for span in spans:
            METRICS.stage_latency.labels(span.name).observe(
                span.duration_s
            )
        self._recorder.record(self)

    def traceparent(self) -> str:
        """The header value we echo: our root span as the parent id."""
        return format_traceparent(self.trace_id, self.root_span_id)

    # -- read surface --

    @staticmethod
    def _stages_view(spans: List[Span]) -> List[Dict[str, Any]]:
        """Top-level spans (parent None) in completion order: the
        request's sequential stage latency breakdown."""
        return [
            {"stage": s.name, "duration_ms": s.duration_s * 1e3}
            for s in spans
            if s.parent is None
        ]

    def stage_breakdown(self) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self._spans)
        return self._stages_view(spans)

    def to_dict(self, include_spans: bool = True) -> Dict[str, Any]:
        with self._lock:
            spans = list(self._spans)
            attrs = dict(self._attrs)
            error = self._error
            duration_s = self.duration_s
            status = self.status
        out: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "name": self.name,
            "status": status,
            "start_unix": self.start_wall,
            "duration_ms": (
                duration_s * 1e3 if duration_s is not None else None
            ),
            "traceparent": self.traceparent(),
            "attributes": attrs,
            "stages": self._stages_view(spans),
        }
        if self.parent_span_id is not None:
            out["parent_span_id"] = self.parent_span_id
        if error is not None:
            out["error"] = error
        if include_spans:
            out["spans"] = [
                {
                    "name": s.name,
                    "parent": s.parent,
                    "start_ms": (s.start - self.start) * 1e3,
                    "duration_ms": s.duration_s * 1e3,
                    "status": s.status,
                    "attributes": s.attrs,
                }
                for s in spans
            ]
        return out


# ------------------------------ the tracer ------------------------------


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        value = int(raw)
        if value <= 0:
            raise ValueError(raw)
        return value
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


@dataclass
class TracerConfig:
    # Fraction of requests traced without an explicit traceparent ask.
    sample_rate: float = DEFAULT_SAMPLE_RATE
    ring_size: int = DEFAULT_RING_SIZE
    slow_threshold_ms: float = DEFAULT_SLOW_THRESHOLD_MS
    slow_keep: int = DEFAULT_SLOW_KEEP
    error_keep: int = DEFAULT_ERROR_KEEP

    @classmethod
    def from_env(cls) -> "TracerConfig":
        return cls(
            sample_rate=_env_float("TRACE_SAMPLE_RATE", DEFAULT_SAMPLE_RATE),
            ring_size=_env_int("TRACE_RING_SIZE", DEFAULT_RING_SIZE),
            slow_threshold_ms=_env_float(
                "TRACE_SLOW_MS", DEFAULT_SLOW_THRESHOLD_MS
            ),
        )


class Tracer:
    """Sampling decisions + trace construction over one recorder."""

    def __init__(self, config: Optional[TracerConfig] = None) -> None:
        self.config = config or TracerConfig.from_env()
        self.recorder = FlightRecorder(
            ring_size=self.config.ring_size,
            slow_keep=self.config.slow_keep,
            error_keep=self.config.error_keep,
            slow_threshold_ms=self.config.slow_threshold_ms,
        )
        self._lock = threading.Lock()
        self._sampled = 0  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock

    def start_trace(
        self,
        name: str,
        traceparent: Optional[str] = None,
        force: bool = False,
    ) -> Optional[Trace]:
        """A new Trace when sampled, else None (count it and move on).

        A valid incoming ``traceparent`` with the sampled flag forces
        tracing and continues the caller's trace id; ``force=True``
        (e.g. ``?explain=1``) does the same with a fresh id.
        """
        parent = parse_traceparent(traceparent)
        if parent is not None and parent.sampled:
            force = True
        if not force:
            rate = self.config.sample_rate
            if rate <= 0.0 or (rate < 1.0 and random.random() >= rate):
                with self._lock:
                    self._dropped += 1
                return None
        with self._lock:
            self._sampled += 1
        return Trace(
            name,
            parent.trace_id if parent is not None else _new_trace_id(),
            _new_span_id(),
            self.recorder,
            parent_span_id=(
                parent.span_id if parent is not None else None
            ),
        )

    def configure(self, **overrides) -> None:
        """Mutate sampling knobs in place (tests, embedding apps).

        Recorder geometry (ring/reservoir sizes) is fixed at
        construction; only ``sample_rate`` and ``slow_threshold_ms``
        are live-tunable.
        """
        for key in ("sample_rate",):
            if key in overrides:
                self.config.sample_rate = float(overrides.pop(key))
        if "slow_threshold_ms" in overrides:
            value = float(overrides.pop("slow_threshold_ms"))
            self.config.slow_threshold_ms = value
            self.recorder.slow_threshold_ms = value
        if overrides:
            raise TypeError(
                f"unknown tracer overrides: {sorted(overrides)}"
            )

    def stats(self) -> dict:
        """Sampling + recorder health for /healthz."""
        with self._lock:
            sampled, dropped = self._sampled, self._dropped
        out = {
            "sample_rate": self.config.sample_rate,
            "traces_sampled": sampled,
            "traces_unsampled": dropped,
        }
        out.update(self.recorder.stats())
        return out

    def reset(self) -> None:
        """Clear recorder + counters (test isolation)."""
        with self._lock:
            self._sampled = 0
            self._dropped = 0
        # gil-atomic: delegates to the recorder's own internal lock
        self.recorder.clear()


# --------------------------- context plumbing ---------------------------

_CURRENT: "contextvars.ContextVar[Optional[Trace]]" = (
    contextvars.ContextVar("kvtpu_trace", default=None)
)


def current_trace() -> Optional[Trace]:
    return _CURRENT.get()


class use_trace:
    """Bind a trace (or None: no-op) to the current context."""

    __slots__ = ("_trace", "_token")

    def __init__(self, trace: Optional[Trace]) -> None:
        self._trace = trace
        self._token = None

    def __enter__(self) -> Optional[Trace]:
        if self._trace is not None:
            self._token = _CURRENT.set(self._trace)
        return self._trace

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        return False


def span(name: str, parent: Optional[str] = None):
    """Span on the context's active trace; free no-op when untraced."""
    trace = _CURRENT.get()
    if trace is None:
        return _NULL_SPAN_CTX
    return trace.span(name, parent)


class shield_trace:
    """Clear the active trace for a scope.

    The process-boundary guard: an in-process wire server (the
    cluster replica's ``handle_wire`` under ``LocalReplicaTransport``
    strict mode) must behave exactly like its cross-process twin —
    server-side spans travel only via the explicit piggyback, never by
    leaking through the caller's context var.
    """

    __slots__ = ("_token",)

    def __init__(self) -> None:
        self._token = None

    def __enter__(self) -> None:
        self._token = _CURRENT.set(None)
        return None

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        return False


# Process-wide tracer, mirroring metrics.collector.METRICS: modules
# import this instead of plumbing a tracer through every constructor.
TRACER = Tracer()
