"""Replay-driven what-if engine: the fleet's wind tunnel.

The replay harness (``obs/replay.py``) answers "did behavior change?"
by re-driving a capture to a bit-identical check.  This module turns
the same artifacts into DECISIONS (ROADMAP item 4):

* **Time-compressed replay** (:func:`run_whatif`) — drive a recorded
  capture at a speed multiplier on a *virtual clock* against a fresh
  candidate stack (single index or a 3-replica ``LocalCluster``),
  measuring real hit rate, score-latency distribution, shed counts,
  and SLO-envelope verdicts under the compressed load.  Determinism is
  structural, not hopeful: the candidate ``Pool`` is never started —
  flow-control decisions happen at enqueue time as pure data-structure
  ops, and the virtual clock owns the only drain
  (``Pool.process_inline``), so the same capture + speed + arm always
  yields the same event interleaving, counters, and digest.  Wall
  clock is used ONLY for reported latencies/throughput and never
  participates in the deterministic pins.  A finite ``drain_rate``
  (events per virtual second) models the candidate's fixed apply
  capacity: raising ``speed`` then raises arrival rate against that
  capacity, reproducing offload-pressure regimes ("Understanding
  Bottlenecks … KV Offloading", PAPERS.md) from real traffic.
* **A/B replay** (:func:`run_ab`) — the same capture through two
  :class:`StackConfig` arms (shards, replicas, backend, eviction
  budget, flow-control knobs), reporting a structured delta: hit
  rate, TTFT-proxy latency percentiles, per-SLI envelope states, and
  the first checkpoint at which the two arms' SLO envelopes diverge.
  "Would this config have held last Tuesday's storm?" gets a measured
  answer from the incident bundle itself.
* **Synthetic composition** (:func:`splice`, :func:`interleave`,
  :func:`scale_pods`, :func:`stretch`, :func:`repeat`) — splice,
  fan-out-multiply, interleave, and time-stretch recorded streams
  into millions-of-users shapes the live bench cannot reach, emitted
  as valid v1 capture artifacts (``obs/capture.encode_capture``) the
  existing replay/divergence machinery accepts.

Surfaces: the CLI (``python -m llm_d_kv_cache_manager_tpu.obs.whatif
run|ab|compose``), ``GET /debug/whatif`` (the bounded results
registry), ``POST /admin/whatif`` (run against a retained incident
bundle), ``kvtpu_whatif_*`` metrics, and the ``hack/perf_trend.py``
gate over the pinned reference capture
(``tests/testdata/whatif_reference.cbor``).  See
docs/observability.md "What-if engine".
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field, fields, replace
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS, safe_label
from llm_d_kv_cache_manager_tpu.obs.capture import (
    canonical_state,
    decode_f64,
    encode_capture,
)
from llm_d_kv_cache_manager_tpu.obs.replay import (
    load_capture,
    render_prompt,
    _ReplayTokenizer,
)
from llm_d_kv_cache_manager_tpu.obs.slo import (
    SloEngine,
    SloSpec,
    envelope_states,
    envelope_violations,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("obs.whatif")

DEFAULT_SPEED = 4.0
DEFAULT_CHECKPOINT_S = 1.0
DEFAULT_WINDOW_FAST_S = 5.0
DEFAULT_WINDOW_SLOW_S = 30.0
DEFAULT_LATENCY_BUDGET_MS = 50.0
DEFAULT_RESULTS_KEEP = 8

# At most this many SLO checkpoints per run: a week-long stretched
# capture must not allocate a million timeline rows, so the effective
# checkpoint interval grows with the virtual span past this.
MAX_CHECKPOINTS = 1024

# The pinned reference capture (hack/make_reference_capture.py) —
# what perf-trend's capacity gate and the smoke replay.
REFERENCE_CAPTURE_RELPATH = os.path.join(
    "tests", "testdata", "whatif_reference.cbor"
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def reference_capture_path() -> str:
    """Absolute path of the checked-in reference capture (exists only
    in a full checkout; callers handle absence)."""
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(root, REFERENCE_CAPTURE_RELPATH)


def resolve_capture_source(path: str) -> str:
    """Accept a capture artifact OR an incident bundle directory (the
    satellite ergonomic: point the CLI at the bundle, not at its
    internals)."""
    if os.path.isdir(path):
        candidate = os.path.join(path, "capture.cbor")
        if not os.path.isfile(candidate):
            raise FileNotFoundError(
                f"{path} is a directory without a capture.cbor "
                "(not an incident bundle?)"
            )
        return candidate
    return path


# ------------------------------ stack config ------------------------------


@dataclass
class StackConfig:
    """One candidate stack (an A/B arm).

    ``parse`` accepts the CLI/admin spec form — comma-separated
    ``key=value`` pairs, e.g. ``"shards=8,mode=cluster,replicas=3"``
    or ``"backend=cost_aware,max_cost_mb=4"``.
    """

    name: str = "a"
    # "single" (one in-memory index) or "cluster" (LocalCluster behind
    # the RemoteIndex).
    mode: str = "single"
    replicas: int = 3
    # "memory" (InMemoryIndex) or "cost_aware" (byte-budgeted LRU with
    # optional predictive eviction — the eviction-policy A/B knob).
    backend: str = "memory"
    shards: int = 0  # 0 -> backend default
    index_size: int = 0  # block-key capacity; 0 -> backend default
    pod_cache: int = 0  # per-key pod entries; 0 -> backend default
    max_cost_mb: float = 64.0  # cost_aware byte budget
    # Event-plane flow control: pool shards, per-shard queue depth
    # (0 -> effectively unbounded), per-pod budget.
    concurrency: int = 1
    depth: int = 0
    pod_budget: Optional[int] = None
    # Load-blended scoring coefficient (None -> LOAD_BLEND env).
    load_blend: Optional[float] = None
    # Apply capacity in events per VIRTUAL second; 0 = unbounded (the
    # stack keeps up perfectly and every score sees every prior
    # admitted write, the replay-parity semantics).
    drain_rate: float = 0.0

    _INT_KEYS = (
        "replicas",
        "shards",
        "index_size",
        "pod_cache",
        "concurrency",
        "depth",
    )
    _FLOAT_KEYS = ("max_cost_mb", "drain_rate")

    @classmethod
    def parse(cls, spec: str, name: str = "a") -> "StackConfig":
        cfg = cls(name=name)
        valid = {f.name for f in fields(cls)}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"arm spec needs key=value pairs, got {part!r}"
                )
            key, value = part.split("=", 1)
            key = key.strip()
            value = value.strip()
            if key == "name" or key.startswith("_") or key not in valid:
                raise ValueError(f"unknown arm knob {key!r}")
            if key in cls._INT_KEYS:
                setattr(cfg, key, int(value))
            elif key in cls._FLOAT_KEYS:
                setattr(cfg, key, float(value))
            elif key in ("pod_budget", "load_blend"):
                setattr(
                    cfg,
                    key,
                    None
                    if value.lower() in ("", "none")
                    else (int(value) if key == "pod_budget" else float(value)),
                )
            else:  # mode / backend
                setattr(cfg, key, value)
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.mode not in ("single", "cluster"):
            raise ValueError(f"unknown mode {self.mode!r}")
        if self.backend not in ("memory", "cost_aware"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.mode == "cluster" and self.backend != "memory":
            raise ValueError(
                "cluster arms use the in-memory backend per replica"
            )
        if self.mode == "cluster" and self.replicas <= 0:
            raise ValueError("cluster arms need replicas >= 1")
        if self.concurrency <= 0:
            raise ValueError("concurrency must be positive")
        if self.drain_rate < 0:
            raise ValueError("drain_rate must be >= 0")

    def describe(self) -> dict:
        out = {
            "name": self.name,
            "mode": self.mode,
            "backend": self.backend,
        }
        if self.mode == "cluster":
            out["replicas"] = self.replicas
        for key in (
            "shards",
            "index_size",
            "pod_cache",
            "concurrency",
            "depth",
        ):
            value = getattr(self, key)
            if value:
                out[key] = value
        if self.backend == "cost_aware":
            out["max_cost_mb"] = self.max_cost_mb
        if self.pod_budget is not None:
            out["pod_budget"] = self.pod_budget
        if self.load_blend is not None:
            out["load_blend"] = self.load_blend
        if self.drain_rate:
            out["drain_rate"] = self.drain_rate
        return out


@dataclass
class WhatIfConfig:
    """Run-shape knobs shared by both arms (docs/configuration.md:
    ``WHATIF_SPEED``, ``WHATIF_CHECKPOINT_S``,
    ``WHATIF_LATENCY_BUDGET_MS``, ``WHATIF_RESULTS_KEEP``)."""

    speed: float = DEFAULT_SPEED
    checkpoint_s: float = DEFAULT_CHECKPOINT_S
    window_fast_s: float = DEFAULT_WINDOW_FAST_S
    window_slow_s: float = DEFAULT_WINDOW_SLOW_S
    latency_budget_ms: float = DEFAULT_LATENCY_BUDGET_MS

    @classmethod
    def from_env(cls) -> "WhatIfConfig":
        return cls(
            speed=_env_float("WHATIF_SPEED", DEFAULT_SPEED),
            checkpoint_s=_env_float(
                "WHATIF_CHECKPOINT_S", DEFAULT_CHECKPOINT_S
            ),
            latency_budget_ms=_env_float(
                "WHATIF_LATENCY_BUDGET_MS", DEFAULT_LATENCY_BUDGET_MS
            ),
        )

    def validate(self) -> None:
        if self.speed <= 0:
            raise ValueError("speed must be positive")
        if self.checkpoint_s <= 0:
            raise ValueError("checkpoint_s must be positive")


# --------------------------- disposition tap ---------------------------


class _DispositionTap:
    """Duck-typed capture recorder attached to the candidate pool: it
    records each offered message's flow-control disposition in offer
    order (the deterministic interleaving the digest folds) instead of
    retaining payloads."""

    def __init__(self) -> None:
        self.events: List[Tuple[str, str, int, str]] = []
        self.admitted = 0
        self.shed = 0
        self.shed_reasons: Dict[str, int] = {}

    def record_admitted_messages(self, messages) -> None:
        for message in messages:
            self.events.append(
                (
                    message.pod_identifier,
                    message.topic,
                    int(message.seq),
                    "admitted",
                )
            )
            self.admitted += 1

    def record_kvevents_batch(self, items) -> None:
        for pod, topic, _model, seq, _gap, _payload, disposition in items:
            self.events.append(
                (str(pod), str(topic), int(seq), str(disposition))
            )
            if disposition == "admitted":
                self.admitted += 1
            else:
                self.shed += 1
                self.shed_reasons[disposition] = (
                    self.shed_reasons.get(disposition, 0) + 1
                )


# ------------------------------ the stack ------------------------------


class _CandidateStack:
    """A fresh index + indexer + (un-started) pool built to one
    :class:`StackConfig` — everything a virtual-clock drive needs."""

    def __init__(self, arm: StackConfig, meta: Dict[str, str]) -> None:
        from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
            Indexer,
            IndexerConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            CostAwareIndexConfig,
            IndexConfig,
            InMemoryIndexConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
            TokenProcessorConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvevents.pool import (
            Pool,
            PoolConfig,
        )
        from llm_d_kv_cache_manager_tpu.tokenization.pool import (
            TokenizationPoolConfig,
        )

        arm.validate()
        block_size = int(meta.get("block_size", 16) or 16)
        hash_seed = str(meta.get("hash_seed", ""))

        in_memory = InMemoryIndexConfig()
        if arm.shards:
            in_memory.shards = arm.shards
        if arm.index_size:
            in_memory.size = arm.index_size
        if arm.pod_cache:
            in_memory.pod_cache_size = arm.pod_cache

        self.cluster = None
        kv_block_index = None
        index_config = IndexConfig(in_memory_config=in_memory)
        if arm.mode == "cluster":
            from llm_d_kv_cache_manager_tpu.cluster import LocalCluster

            self.cluster = LocalCluster(
                [f"whatif-{i}" for i in range(max(1, arm.replicas))],
                index_config=in_memory,
            )
            kv_block_index = self.cluster.remote_index
        elif arm.backend == "cost_aware":
            index_config = IndexConfig(
                in_memory_config=None,
                cost_aware_config=CostAwareIndexConfig(
                    max_cost_bytes=int(
                        max(1.0, arm.max_cost_mb) * 1024 * 1024
                    ),
                    pod_cache_size=arm.pod_cache or 10,
                ),
            )

        self.indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=block_size, hash_seed=hash_seed
                ),
                kvblock_index_config=index_config,
                tokenizers_pool_config=TokenizationPoolConfig(
                    # Recorded token streams are the SERVED streams;
                    # the candidate's prefix store must never
                    # re-truncate them (same pin as obs/replay.py).
                    min_prefix_overlap_ratio=1.1,
                ),
                cache_stats=False,
                load_blend=arm.load_blend,
            ),
            tokenizer=_ReplayTokenizer(),
            kv_block_index=kv_block_index,
        )
        self.indexer.run()
        self.tap = _DispositionTap()
        # NEVER started: the virtual clock owns the only drain
        # (Pool.process_inline), so enqueue/shed/apply interleaving is
        # a pure function of the schedule.
        self.pool = Pool(
            self.indexer.kv_block_index,
            self.indexer.token_processor,
            PoolConfig(
                concurrency=max(1, arm.concurrency),
                max_queue_depth=arm.depth if arm.depth > 0 else 1 << 30,
                pod_budget=arm.pod_budget,
            ),
            capture=self.tap,
        )

    def close(self) -> None:
        self.pool.shutdown()
        self.indexer.shutdown()
        if self.cluster is not None:
            self.cluster.close()


def _register_slos(
    engine: SloEngine,
    counters: Dict[str, int],
    tap: _DispositionTap,
    pool,
) -> None:
    """The replayed-stream SLIs evaluated on the VIRTUAL clock.  Shed
    fraction, hit rate, and backlog are deterministic; score latency
    is wall-measured (a real TTFT proxy) and intentionally excluded
    from the determinism pins."""
    engine.register(
        SloSpec(
            "whatif.event_shed",
            kind="ratio",
            objective=0.99,
            degraded_bound=0.90,
            description="offered kvevents neither rejected nor "
            "displaced by the candidate stack's flow control",
        ),
        lambda: (
            (max(0, counters["offered"] - tap.shed), counters["offered"])
            if counters["offered"]
            else None
        ),
    )
    engine.register(
        SloSpec(
            "whatif.hit_rate",
            kind="ratio",
            objective=0.25,
            degraded_bound=0.05,
            description="scored requests with a non-zero best score "
            "under the replayed load",
        ),
        lambda: (
            (counters["hits"], counters["scores"])
            if counters["scores"]
            else None
        ),
    )
    engine.register(
        SloSpec(
            "whatif.score_latency",
            kind="ratio",
            objective=0.95,
            degraded_bound=0.80,
            description="scores answered within WHATIF_LATENCY_BUDGET_MS "
            "(wall-measured TTFT proxy; not part of the deterministic "
            "pins)",
        ),
        lambda: (
            (counters["lat_good"], counters["scores"])
            if counters["scores"]
            else None
        ),
    )
    engine.register(
        SloSpec(
            "whatif.backlog",
            kind="gauge",
            objective=512.0,
            degraded_bound=65536.0,
            gauge_agg="max",
            description="candidate pool backlog (queued, not yet "
            "applied) at the checkpoint",
        ),
        lambda: float(pool.backlog()),
    )


def _percentile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    pos = min(
        len(sorted_values) - 1, int(q * (len(sorted_values) - 1) + 0.5)
    )
    return sorted_values[pos]


# ------------------------------ the drive ------------------------------


def run_whatif(
    capture: dict,
    arm: Optional[StackConfig] = None,
    config: Optional[WhatIfConfig] = None,
    register: bool = True,
) -> dict:
    """Time-compressed replay of one loaded capture through one
    candidate arm; returns the machine-readable result (and records it
    in the ``/debug/whatif`` registry unless ``register=False``).

    Deterministic fields for a given (capture, speed, arm):
    ``events``, ``scores.total/hits/hit_rate/recorded_parity``,
    ``digest``, ``seq_classification_mismatches``.  Wall-clock fields
    (``latency_ms``, ``wall_s``, throughput) vary run to run.
    """
    from llm_d_kv_cache_manager_tpu.kvevents.pool import Message
    from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
        TopicSeqTracker,
    )

    arm = arm or StackConfig()
    config = config or WhatIfConfig.from_env()
    config.validate()
    records = capture["records"]
    if not records:
        raise ValueError("capture holds no records")
    meta = capture.get("meta") or {}

    ts_values = [int(r[2]) for r in records]
    t0 = min(ts_values)
    span_virtual = max(0.0, (max(ts_values) - t0) / 1e6 / config.speed)
    checkpoint_s = max(
        config.checkpoint_s, span_virtual / MAX_CHECKPOINTS
    )

    counters: Dict[str, int] = {
        "offered": 0,
        "shed": 0,
        "applied": 0,
        "scores": 0,
        "hits": 0,
        "lat_good": 0,
        "parity": 0,
    }
    stack = _CandidateStack(arm, meta)
    engine = SloEngine(
        window_fast_s=config.window_fast_s,
        window_slow_s=max(config.window_slow_s, config.window_fast_s),
    )
    _register_slos(engine, counters, stack.tap, stack.pool)

    digest = hashlib.blake2b(digest_size=16)
    timeline: List[Tuple[float, Dict[str, str]]] = []
    latencies: List[float] = []
    trackers: Dict[str, TopicSeqTracker] = {}
    mismatches = 0
    drain_rate = float(arm.drain_rate)
    credit = 0.0
    # Token-bucket burst bound: one virtual second of capacity (at
    # least one apply batch) — idle stretches must not bank unlimited
    # catch-up credit or the backlog model goes soft.
    burst = max(drain_rate, 32.0)
    last_v = 0.0
    next_cp = checkpoint_s
    tap = stack.tap
    tap_cursor = 0
    peak_backlog = 0
    wall_t0 = time.perf_counter()

    def checkpoint(now_v: float) -> None:
        engine.sample(now=now_v)
        payload = engine.evaluate(now=now_v)
        timeline.append((round(now_v, 6), envelope_states(payload)))

    try:
        for record in records:
            v = max(0.0, (int(record[2]) - t0) / 1e6 / config.speed)
            if drain_rate > 0.0 and v > last_v:
                credit = min(credit + (v - last_v) * drain_rate, burst)
                budget = int(credit)
                if budget > 0:
                    counters["applied"] += stack.pool.process_inline(
                        budget
                    )
                    credit -= budget
            last_v = max(last_v, v)
            while v >= next_cp:
                checkpoint(next_cp)
                next_cp += checkpoint_s
            if record[0] == 0:
                (
                    _kind,
                    _seq,
                    _ts,
                    pod,
                    topic,
                    model,
                    msg_seq,
                    seq_gap,
                    payload,
                    _disposition,
                ) = record
                if payload is None:
                    # Displacement notice / payload-free shed: the
                    # admit-time record (which carries the payload)
                    # is the offer; what-if re-decides its fate.
                    continue
                tracker = trackers.get(str(pod))
                if tracker is None:
                    tracker = trackers[str(pod)] = TopicSeqTracker()
                observed = tracker.observe(str(topic), int(msg_seq))
                if int(observed.gap) != int(seq_gap):
                    mismatches += 1
                counters["offered"] += 1
                stack.pool.add_task(
                    Message(
                        topic=str(topic),
                        payload=bytes(payload),
                        pod_identifier=str(pod),
                        model_name=str(model),
                        seq=int(msg_seq),
                        seq_gap=int(observed.gap),
                    )
                )
                backlog = stack.pool.backlog()
                if backlog > peak_backlog:
                    peak_backlog = backlog
                if (
                    drain_rate == 0.0
                    and counters["offered"] % 4096 == 0
                ):
                    counters["applied"] += stack.pool.process_inline()
            else:
                _kind, seq, _ts, model, tokens, pods, raw_scores = record
                if drain_rate == 0.0:
                    # Unbounded capacity: replay-parity semantics —
                    # every admitted write is visible to this read.
                    counters["applied"] += stack.pool.process_inline()
                score_t0 = time.perf_counter()
                got = stack.indexer.get_pod_scores(
                    render_prompt(tokens),
                    str(model),
                    [str(p) for p in pods] if pods is not None else None,
                )
                elapsed_ms = (time.perf_counter() - score_t0) * 1e3
                latencies.append(elapsed_ms)
                counters["scores"] += 1
                if any(value > 0.0 for value in got.values()):
                    counters["hits"] += 1
                if elapsed_ms <= config.latency_budget_ms:
                    counters["lat_good"] += 1
                recorded = {
                    str(p): decode_f64(value) for p, value in raw_scores
                }
                if got == recorded:
                    counters["parity"] += 1
                digest.update(
                    f"s|{seq}|{sorted(got.items())!r}\n".encode()
                )
            # Fold newly-decided dispositions in interleaved order.
            events = tap.events
            while tap_cursor < len(events):
                pod_id, topic_id, mseq, dispo = events[tap_cursor]
                digest.update(
                    f"e|{pod_id}|{topic_id}|{mseq}|{dispo}\n".encode()
                )
                tap_cursor += 1

        final_backlog = stack.pool.backlog()
        counters["applied"] += stack.pool.process_inline()
        end_v = max(span_virtual, next_cp - checkpoint_s) + checkpoint_s
        checkpoint(end_v)
        final_payload = engine.evaluate(now=end_v)
        state = canonical_state(stack.indexer.kv_block_index)
        digest.update(repr(state).encode())
        digest.update(
            f"c|{counters['offered']}|{tap.admitted}|{tap.shed}|"
            f"{counters['scores']}|{counters['hits']}|"
            f"{final_backlog}\n".encode()
        )
    finally:
        stack.close()

    wall_s = max(1e-9, time.perf_counter() - wall_t0)
    latencies_sorted = sorted(latencies)
    scores_total = counters["scores"]
    result = {
        "kind": "run",
        "arm": arm.name,
        "config": arm.describe(),
        "speed": config.speed,
        "drain_rate": drain_rate,
        "virtual_span_s": round(span_virtual, 6),
        "checkpoint_s": checkpoint_s,
        "wall_s": wall_s,
        "events": {
            "offered": counters["offered"],
            "admitted": tap.admitted,
            "shed": tap.shed,
            "shed_reasons": dict(sorted(tap.shed_reasons.items())),
            "applied": counters["applied"],
            "final_backlog": final_backlog,
            "peak_backlog": peak_backlog,
            "per_sec_wall": counters["offered"] / wall_s,
        },
        "scores": {
            "total": scores_total,
            "hits": counters["hits"],
            "hit_rate": (
                counters["hits"] / scores_total if scores_total else 0.0
            ),
            "recorded_parity": (
                counters["parity"] / scores_total if scores_total else 0.0
            ),
            "latency_ms": {
                "p50": _percentile(latencies_sorted, 0.50),
                "p90": _percentile(latencies_sorted, 0.90),
                "p99": _percentile(latencies_sorted, 0.99),
            },
            "per_sec_wall": scores_total / wall_s,
        },
        "seq_classification_mismatches": mismatches,
        "slo": {
            "final": envelope_states(final_payload),
            "violations": envelope_violations(final_payload),
            "checkpoints": len(timeline),
            "timeline": [
                [v, states] for v, states in timeline
            ],
        },
        "digest": digest.hexdigest(),
    }
    _account_run(result, outcome="ok")
    if register:
        REGISTRY.add(result)
    return result


def _account_run(result: dict, outcome: str) -> None:
    try:
        METRICS.whatif_runs.labels(
            kind=result.get("kind", "run"), outcome=outcome
        ).inc()
        events = result.get("events") or {}
        for disposition, count in (
            ("admitted", events.get("admitted", 0)),
            ("shed", events.get("shed", 0)),
        ):
            if count:
                METRICS.whatif_events.labels(
                    disposition=disposition
                ).inc(count)
        scores = result.get("scores") or {}
        METRICS.whatif_hit_rate.labels(
            arm=safe_label(str(result.get("arm", "a")))
        ).set(float(scores.get("hit_rate", 0.0)))
    except Exception:  # noqa: BLE001 — metrics must never fail a run
        logger.exception("whatif metrics accounting failed")


# ------------------------------- A/B replay -------------------------------


def first_slo_divergence(
    timeline_a: Sequence[Sequence],
    timeline_b: Sequence[Sequence],
) -> Optional[dict]:
    """The first checkpoint at which the two arms' envelope states
    differ (per-SLI), or ``None`` when they never do."""
    for (v_a, states_a), (v_b, states_b) in zip(timeline_a, timeline_b):
        if states_a != states_b:
            differing = sorted(
                name
                for name in set(states_a) | set(states_b)
                if states_a.get(name) != states_b.get(name)
            )
            return {
                "virtual_s": v_a,
                "slis": differing,
                "a": {name: states_a.get(name) for name in differing},
                "b": {name: states_b.get(name) for name in differing},
            }
    return None


def _pair(a_value, b_value) -> dict:
    out = {"a": a_value, "b": b_value}
    if isinstance(a_value, (int, float)) and isinstance(
        b_value, (int, float)
    ):
        out["delta"] = b_value - a_value
    return out


def run_ab(
    capture: dict,
    arm_a: StackConfig,
    arm_b: StackConfig,
    config: Optional[WhatIfConfig] = None,
    register: bool = True,
) -> dict:
    """Same capture, two arms, one structured delta (the ISSUE's
    machine-readable A/B verdict).  Arms run sequentially against
    fresh stacks; both see the identical virtual schedule."""
    config = config or WhatIfConfig.from_env()
    if arm_a.name == arm_b.name:
        arm_b = replace(arm_b, name=arm_b.name + "-b")
    a = run_whatif(capture, arm_a, config, register=False)
    b = run_whatif(capture, arm_b, config, register=False)
    hit_a = a["scores"]["hit_rate"]
    hit_b = b["scores"]["hit_rate"]
    if hit_a == hit_b:
        hit_parity = 1.0
    else:
        low, high = sorted((hit_a, hit_b))
        hit_parity = (low / high) if high > 0 else 0.0
    delta = {
        "hit_rate": _pair(hit_a, hit_b),
        "hit_parity": hit_parity,
        "recorded_parity": _pair(
            a["scores"]["recorded_parity"], b["scores"]["recorded_parity"]
        ),
        "shed": _pair(a["events"]["shed"], b["events"]["shed"]),
        "applied": _pair(a["events"]["applied"], b["events"]["applied"]),
        "final_backlog": _pair(
            a["events"]["final_backlog"], b["events"]["final_backlog"]
        ),
        "latency_p50_ms": _pair(
            a["scores"]["latency_ms"]["p50"],
            b["scores"]["latency_ms"]["p50"],
        ),
        "latency_p99_ms": _pair(
            a["scores"]["latency_ms"]["p99"],
            b["scores"]["latency_ms"]["p99"],
        ),
        "wall_scores_per_sec": _pair(
            a["scores"]["per_sec_wall"], b["scores"]["per_sec_wall"]
        ),
        "digest_equal": a["digest"] == b["digest"],
        "slo": {
            "a_final": a["slo"]["final"],
            "b_final": b["slo"]["final"],
            "first_divergence": first_slo_divergence(
                a["slo"]["timeline"], b["slo"]["timeline"]
            ),
        },
    }
    result = {
        "kind": "ab",
        "speed": config.speed,
        "a": a,
        "b": b,
        "delta": delta,
    }
    _account_run(
        {"kind": "ab", "arm": "ab", "events": {}, "scores": {}},
        outcome="ok",
    )
    if register:
        REGISTRY.add(result)
    return result


def gate_headlines(ab: dict) -> Dict[str, float]:
    """The deterministic higher-is-better headlines perf-trend gates
    on the pinned reference capture (hack/perf_trend.py):

    * ``whatif.hit_rate`` — arm A's measured hit rate (a hashing /
      chunking / index regression zeroes or dents it);
    * ``whatif.recorded_parity`` — fraction of replayed scores equal
      to the recorded maps (ANY behavioral drift shows here first);
    * ``whatif.ab_hit_parity`` — hit-rate parity between the two index
      configs (a shard-count-dependent scoring bug breaks it).
    """
    delta = ab["delta"]
    return {
        "whatif.hit_rate": float(delta["hit_rate"]["a"]),
        "whatif.recorded_parity": float(delta["recorded_parity"]["a"]),
        "whatif.ab_hit_parity": float(delta["hit_parity"]),
    }


def reference_ab(
    capture_path: Optional[str] = None,
    config: Optional[WhatIfConfig] = None,
) -> dict:
    """The pinned capacity check: A/B of ``shards=1`` vs ``shards=8``
    over the reference capture — deterministic headline values on any
    machine (hit rate, recorded parity, A/B parity)."""
    path = capture_path or reference_capture_path()
    # The fingerprint hashes the package version; the checked-in
    # artifact intentionally survives version bumps, and what-if
    # measures rather than bit-compares, so mismatch is allowed.
    capture = load_capture(
        resolve_capture_source(path), allow_mismatch=True
    )
    return run_ab(
        capture,
        StackConfig.parse("shards=1", name="shards1"),
        StackConfig.parse("shards=8", name="shards8"),
        config or WhatIfConfig(speed=DEFAULT_SPEED),
        register=False,
    )


# ---------------------------- results registry ----------------------------

# kvlint: lock-order: WhatIfRegistry._lock ascending
lockorder.declare_ascending("WhatIfRegistry._lock")


def _drop_none(payload: dict) -> dict:
    return {k: v for k, v in payload.items() if v is not None}


def _summarize(result: dict) -> dict:
    """One-line view for /debug/whatif listings."""
    if result.get("kind") == "ab":
        delta = result.get("delta") or {}
        return _drop_none(
            {
                "kind": "ab",
                "speed": result.get("speed"),
                "hit_rate": delta.get("hit_rate"),
                "shed": delta.get("shed"),
                "digest_equal": delta.get("digest_equal"),
                "first_divergence": (delta.get("slo") or {}).get(
                    "first_divergence"
                ),
                "completed_unix": result.get("completed_unix"),
            }
        )
    events = result.get("events") or {}
    scores = result.get("scores") or {}
    return _drop_none(
        {
            "kind": result.get("kind", "run"),
            "arm": result.get("arm"),
            "speed": result.get("speed"),
            "offered": events.get("offered"),
            "shed": events.get("shed"),
            "hit_rate": scores.get("hit_rate"),
            "slo_final": (result.get("slo") or {})
            .get("final", {})
            .get("overall"),
            "digest": result.get("digest"),
            "completed_unix": result.get("completed_unix"),
        }
    )


class WhatIfRegistry:
    """Bounded ring of completed run/A-B results — the
    ``GET /debug/whatif`` surface (``WHATIF_RESULTS_KEEP``)."""

    def __init__(self, keep: int = DEFAULT_RESULTS_KEEP) -> None:
        self.keep = max(1, keep)
        self._lock = lockorder.tracked(
            threading.Lock(), "WhatIfRegistry._lock"
        )
        self._results: Deque[dict] = deque(
            maxlen=self.keep
        )  # guarded-by: _lock

    def add(self, result: dict) -> None:
        result = dict(result)
        result.setdefault("completed_unix", time.time())
        with self._lock:
            self._results.append(result)

    def list(self, full: bool = False) -> List[dict]:
        with self._lock:
            results = list(self._results)
        results.reverse()  # newest first
        if full:
            return results
        return [_summarize(result) for result in results]

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._results[-1] if self._results else None

    def status(self) -> dict:
        with self._lock:
            count = len(self._results)
            last = self._results[-1] if self._results else None
        return {
            "results": count,
            "keep": self.keep,
            "last": _summarize(last) if last else None,
        }


REGISTRY = WhatIfRegistry(
    keep=_env_int("WHATIF_RESULTS_KEEP", DEFAULT_RESULTS_KEEP)
)


# ----------------------------- composition -----------------------------


def _require_compatible(captures: Sequence[dict]) -> None:
    if not captures:
        raise ValueError("composition needs at least one capture")
    base = captures[0].get("meta") or {}
    for capture in captures[1:]:
        meta = capture.get("meta") or {}
        for key in ("block_size", "hash_seed"):
            if str(meta.get(key, "")) != str(base.get(key, "")):
                raise ValueError(
                    f"incompatible captures: meta {key} differs "
                    f"({base.get(key)!r} vs {meta.get(key)!r})"
                )


def _renumber(records: List[list]) -> List[list]:
    for seq, record in enumerate(records, start=1):
        record[1] = seq
    return records


def _compose_result(
    base: dict,
    records: List[list],
    ops_note: str,
    state: Optional[list],
) -> dict:
    meta = dict(base.get("meta") or {})
    prior = meta.get("compose_ops", "")
    meta["composed"] = "1"
    meta["compose_ops"] = f"{prior}+{ops_note}" if prior else ops_note
    return {
        "fingerprint": base["fingerprint"],
        "knobs": list(base["knobs"]),
        "created_us": int(base.get("created_us", 0)),
        "window_s": int(base.get("window_s", 0)),
        "max_bytes": int(base.get("max_bytes", 0)),
        "truncated": sorted(
            {
                source
                for capture in (base,)
                for source in (capture.get("truncated") or [])
            }
        ),
        "meta": meta,
        "records": _renumber(records),
        "state": state,
    }


def capture_to_bytes(capture: dict) -> bytes:
    """Serialize a loaded/composed capture dict back to a valid v1
    artifact (``load_capture``-compatible round trip)."""
    return encode_capture(
        capture["records"],
        fingerprint=capture["fingerprint"],
        knobs=capture["knobs"],
        created_us=capture.get("created_us", 0),
        window_s=capture.get("window_s", 0),
        max_bytes=capture.get("max_bytes", 0),
        truncated=capture.get("truncated") or [],
        meta=capture.get("meta") or {},
        state=capture.get("state"),
    )


def splice(captures: Sequence[dict], gap_us: int = 1_000_000) -> dict:
    """Play captures back-to-back on one timeline: capture *k+1*
    starts ``gap_us`` after capture *k* ends, and each (pod, topic)
    publisher seq stream is offset to CONTINUE the prior segment's
    stream — every recorded gap classification replays identically
    (the boundary record's offset preserves its recorded gap).  State
    and recorded scores describe the SOURCE segments, so the spliced
    artifact drops its state section (what-if measures; bit-exact
    replay of a splice is only meaningful segment by segment)."""
    _require_compatible(captures)
    out: List[list] = []
    last_ts = 0
    # (pod, topic) -> last msg seq emitted on the spliced timeline
    # (the replayed TopicSeqTracker watermark).
    watermark: Dict[Tuple[str, str], int] = {}
    for idx, capture in enumerate(captures):
        records = capture["records"]
        if not records:
            continue
        first_ts = min(int(r[2]) for r in records)
        shift = 0 if idx == 0 else (last_ts + gap_us - first_ts)
        # Per-stream seq offset for THIS segment, fixed at the
        # stream's first record so internal deltas are preserved.
        offsets: Dict[Tuple[str, str], int] = {}
        for record in records:
            row = [
                value if not isinstance(value, list) else list(value)
                for value in record
            ]
            row[2] = int(row[2]) + shift
            if row[0] == 0:
                key = (str(row[3]), str(row[4]))
                if key not in offsets:
                    prior = watermark.get(key)
                    if prior is None:
                        offsets[key] = 0
                    else:
                        # Continue the stream: the first record keeps
                        # its recorded gap (new_seq - prior - 1 ==
                        # recorded gap).
                        offsets[key] = (
                            prior + 1 + int(row[7]) - int(row[6])
                        )
                row[6] = int(row[6]) + offsets[key]
                watermark[key] = row[6]
            out.append(row)
        last_ts = max(int(r[2]) + shift for r in records)
    return _compose_result(
        captures[0], out, f"splice:{len(captures)}", state=None
    )


def repeat(capture: dict, times: int, gap_us: int = 1_000_000) -> dict:
    """Splice a capture with itself ``times`` times — the sustained
    re-arrival storm shape."""
    if times < 1:
        raise ValueError("repeat needs times >= 1")
    return splice([capture] * times, gap_us=gap_us)


def _rename_pod_topic(topic: str, pod: str, clone: str, tag: str) -> str:
    if pod and pod in topic:
        return topic.replace(pod, clone, 1)
    return f"{topic}{tag}"


def scale_pods(capture: dict, factor: int) -> dict:
    """Pod-fanout multiply: every kvevents stream is cloned under
    ``factor - 1`` derived pod identities (identical payload bytes,
    identical seq stream), and every recorded score map / pod filter /
    state entry is expanded to the clones — the clones hold exactly
    the original pods' blocks, so within the index's per-key pod-cache
    capacity the scaled artifact still replays bit-exactly through
    ``obs/replay.replay_capture``.  When the expansion would overflow
    the default pod cache the state section is dropped (scores remain
    recorded truth per construction)."""
    if factor < 1:
        raise ValueError("scale factor must be >= 1")
    records = capture["records"]
    out: List[list] = []
    max_pods_per_key = 0
    for record in records:
        if record[0] == 0:
            base_row = [
                value if not isinstance(value, list) else list(value)
                for value in record
            ]
            out.append(base_row)
            pod = str(record[3])
            for k in range(1, factor):
                clone = f"{pod}x{k}"
                row = list(base_row)
                row[3] = clone
                row[4] = _rename_pod_topic(
                    str(record[4]), pod, clone, f"x{k}"
                )
                out.append(row)
        else:
            kind, seq, ts, model, tokens, pods, raw_scores = record
            new_pods = None
            if pods is not None:
                new_pods = []
                for pod in pods:
                    new_pods.append(pod)
                    new_pods.extend(
                        f"{pod}x{k}" for k in range(1, factor)
                    )
            new_scores = []
            for pod, value in raw_scores:
                new_scores.append([pod, value])
                new_scores.extend(
                    [f"{pod}x{k}", value] for k in range(1, factor)
                )
            new_scores.sort(key=lambda item: str(item[0]))
            out.append(
                [
                    kind,
                    seq,
                    ts,
                    model,
                    list(tokens),
                    new_pods,
                    new_scores,
                ]
            )
    state = capture.get("state")
    new_state = None
    if state is not None and factor >= 1:
        block_rows = []
        for key, entries in state[0]:
            expanded = []
            for pod, tier in entries:
                expanded.append([pod, tier])
                expanded.extend(
                    [f"{pod}x{k}", tier] for k in range(1, factor)
                )
            expanded.sort(key=lambda item: (str(item[0]), str(item[1])))
            max_pods_per_key = max(max_pods_per_key, len(expanded))
            block_rows.append([key, expanded])
        # InMemoryIndexConfig.pod_cache_size default — past it the
        # replayed index evicts pod entries the recorded state keeps.
        if max_pods_per_key <= 10:
            new_state = [block_rows, [list(row) for row in state[1]]]
    return _compose_result(
        capture, out, f"scale:{factor}", state=new_state
    )


def interleave(captures: Sequence[dict]) -> dict:
    """Overlay captures on ONE timeline (offset to a common origin),
    renaming every stream of capture *k>0* (``~s<k>`` pod suffix) so
    publisher seq streams never collide — the concurrent-fleets storm
    shape.  Scores keep their per-stream pod filters (renamed); the
    state section is dropped (streams sharing token chains would
    cross-pollinate score maps, which is exactly the load shape this
    operator exists to create, measured by what-if rather than
    bit-compared)."""
    _require_compatible(captures)
    rows: List[Tuple[int, int, int, list]] = []
    for idx, capture in enumerate(captures):
        records = capture["records"]
        if not records:
            continue
        first_ts = min(int(r[2]) for r in records)
        suffix = f"~s{idx}"
        for record in records:
            row = [
                value if not isinstance(value, list) else list(value)
                for value in record
            ]
            row[2] = int(row[2]) - first_ts
            if idx > 0:
                if row[0] == 0:
                    pod = str(row[3])
                    clone = pod + suffix
                    row[3] = clone
                    row[4] = _rename_pod_topic(
                        str(row[4]), pod, clone, suffix
                    )
                else:
                    if row[5] is not None:
                        row[5] = [str(p) + suffix for p in row[5]]
                    row[6] = [
                        [str(p) + suffix, value] for p, value in row[6]
                    ]
            rows.append((row[2], idx, int(record[1]), row))
    rows.sort(key=lambda item: (item[0], item[1], item[2]))
    base_t0 = int(captures[0].get("created_us", 0))
    out = []
    for offset, _idx, _seq, row in rows:
        row[2] = base_t0 + offset
        out.append(row)
    return _compose_result(
        captures[0], out, f"interleave:{len(captures)}", state=None
    )


def stretch(capture: dict, factor: float) -> dict:
    """Time-stretch (factor > 1) or compress (factor < 1) the recorded
    timeline around its first timestamp.  Replay semantics are
    timestamp-free, so a stretched capture still replays bit-exactly;
    what-if's virtual clock sees the new arrival density."""
    if factor <= 0:
        raise ValueError("stretch factor must be positive")
    records = capture["records"]
    if not records:
        raise ValueError("capture holds no records")
    t0 = min(int(r[2]) for r in records)
    out = []
    for record in records:
        row = [
            value if not isinstance(value, list) else list(value)
            for value in record
        ]
        row[2] = t0 + int(round((int(row[2]) - t0) * factor))
        out.append(row)
    return _compose_result(
        capture,
        out,
        f"stretch:{factor:g}",
        state=capture.get("state"),
    )


# --------------------------------- CLI ---------------------------------


def _load(path: str, allow_mismatch: bool) -> dict:
    return load_capture(
        resolve_capture_source(path), allow_mismatch=allow_mismatch
    )


def _apply_ops(captures: List[dict], ops: List[str]) -> dict:
    """Apply composition ops left to right.  ``splice`` /
    ``interleave`` consume the current capture LIST; ``scale:<n>`` /
    ``stretch:<f>`` / ``repeat:<n>`` transform the current (single)
    capture."""
    current: Optional[dict] = captures[0] if len(captures) == 1 else None
    for op in ops:
        name, _, arg = op.partition(":")
        name = name.strip().lower()
        if name in ("splice", "interleave"):
            pool = captures if current is None else [current]
            current = (
                splice(pool) if name == "splice" else interleave(pool)
            )
        elif name == "scale":
            if current is None:
                current = splice(captures)
            current = scale_pods(current, int(arg or "2"))
        elif name == "stretch":
            if current is None:
                current = splice(captures)
            current = stretch(current, float(arg or "1"))
        elif name == "repeat":
            if current is None:
                current = splice(captures)
            current = repeat(current, int(arg or "2"))
        else:
            raise ValueError(f"unknown compose op {op!r}")
    if current is None:
        current = splice(captures)
    return current


def _emit(result: dict, json_path: Optional[str]) -> None:
    if json_path:
        with open(json_path, "w") as handle:
            json.dump(result, handle, indent=2, default=str)
        print(f"whatif: full result written to {json_path}")
    print(json.dumps(_summarize(result), indent=2, default=str))


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m llm_d_kv_cache_manager_tpu.obs.whatif",
        description="Replay-driven what-if engine: time-compressed "
        "replay, A/B config canarying, synthetic capture composition "
        "(docs/observability.md)",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    def add_common(p) -> None:
        p.add_argument(
            "capture",
            help="capture artifact path OR incident bundle directory",
        )
        p.add_argument(
            "--speed",
            type=float,
            default=None,
            help="time-compression multiplier (default WHATIF_SPEED)",
        )
        p.add_argument(
            "--strict-fingerprint",
            action="store_true",
            help="refuse mismatched captures (default: measure anyway)",
        )
        p.add_argument(
            "--json", default=None, help="write the full result here"
        )

    p_run = sub.add_parser(
        "run", help="time-compressed replay through one candidate arm"
    )
    add_common(p_run)
    p_run.add_argument(
        "--arm",
        default="",
        help="arm spec, e.g. shards=8,mode=cluster,drain_rate=500",
    )

    p_ab = sub.add_parser(
        "ab", help="same capture through two arms; structured delta"
    )
    add_common(p_ab)
    p_ab.add_argument("--a", default="shards=1", help="arm A spec")
    p_ab.add_argument("--b", default="shards=8", help="arm B spec")

    p_comp = sub.add_parser(
        "compose",
        help="splice/scale/interleave/stretch captures into a new "
        "artifact",
    )
    p_comp.add_argument("output", help="output artifact path")
    p_comp.add_argument(
        "inputs", nargs="+", help="input captures / bundle dirs"
    )
    p_comp.add_argument(
        "--op",
        action="append",
        default=[],
        help="operator, repeatable: splice | interleave | scale:<n> | "
        "stretch:<f> | repeat:<n> (applied left to right)",
    )
    p_comp.add_argument(
        "--strict-fingerprint",
        action="store_true",
        help="refuse mismatched captures",
    )

    args = parser.parse_args(argv)
    config = WhatIfConfig.from_env()
    if getattr(args, "speed", None):
        config.speed = args.speed

    if args.command == "run":
        capture = _load(args.capture, not args.strict_fingerprint)
        arm = StackConfig.parse(args.arm, name="a")
        result = run_whatif(capture, arm, config)
        _emit(result, args.json)
        return 0
    if args.command == "ab":
        capture = _load(args.capture, not args.strict_fingerprint)
        result = run_ab(
            capture,
            StackConfig.parse(args.a, name="a"),
            StackConfig.parse(args.b, name="b"),
            config,
        )
        _emit(result, args.json)
        return 0
    # compose
    captures = [
        _load(path, not args.strict_fingerprint) for path in args.inputs
    ]
    composed = _apply_ops(captures, args.op or ["splice"])
    payload = capture_to_bytes(composed)
    with open(args.output, "wb") as handle:
        handle.write(payload)
    print(
        json.dumps(
            {
                "output": args.output,
                "bytes": len(payload),
                "records": len(composed["records"]),
                "meta": composed["meta"],
            },
            indent=2,
        )
    )
    return 0


if __name__ == "__main__":  # pragma: no cover - CLI entry
    sys.exit(main())
