from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper  # noqa: F401
from llm_d_kv_cache_manager_tpu.offload.manager import (  # noqa: F401
    SharedStorageOffloadManager,
)
from llm_d_kv_cache_manager_tpu.offload.spec import (  # noqa: F401
    TPUOffloadConnector,
    TPUOffloadSpec,
)
from llm_d_kv_cache_manager_tpu.offload.staging_engine import (  # noqa: F401
    StagingConfig,
    StagingEngine,
    StagingSaturated,
)
from llm_d_kv_cache_manager_tpu.offload.worker import (  # noqa: F401
    DeviceToStorageHandler,
    StorageToDeviceHandler,
)
