"""Block-hash -> shared-storage path mapping.

Layout parity with the reference connector (kv_connectors/llmd_fs_backend/
llmd_fs_backend/file_mapper.py:40-88) so fleets can mix GPU and TPU pods on
one shared filesystem:

    <root>/<model>
          /block_size_<device_block_size>_blocks_per_file_<blocks_per_file>
          /tp_<tp>_pp_size_<pp>_pcp_size_<pcp>
          /rank_<rank>
          /<dtype>
          /<hhh>/<hh>/<hash16>.bin

On TPU the tp/pp/pcp axes come from the device mesh shape: each mesh-rank
offloads only its own KV shard, and a pod with the same mesh layout can
load any other pod's shards rank-for-rank.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

_MASK64 = 0xFFFFFFFFFFFFFFFF


@dataclass(frozen=True)
class FileMapper:
    root_dir: str
    model_name: str
    device_block_size: int
    blocks_per_file: int
    tp_size: int = 1
    pp_size: int = 1
    pcp_size: int = 1
    rank: int = 0
    dtype: str = "bfloat16"

    @property
    def base_path(self) -> str:
        return os.path.join(
            self.root_dir,
            self.model_name,
            f"block_size_{self.device_block_size}"
            f"_blocks_per_file_{self.blocks_per_file}",
            f"tp_{self.tp_size}_pp_size_{self.pp_size}"
            f"_pcp_size_{self.pcp_size}",
            f"rank_{self.rank}",
            self.dtype,
        )

    def get_file_name(self, block_hash) -> str:
        """Path for one offloaded block; hash-prefix subdirs bound the
        per-directory fan-out."""
        if isinstance(block_hash, (bytes, bytearray)):
            block_hash = int.from_bytes(block_hash, "little")
        hash_hex = f"{block_hash & _MASK64:016x}"
        return os.path.join(
            self.base_path,
            hash_hex[:3],
            hash_hex[3:5],
            f"{hash_hex}.bin",
        )
