"""Host-DRAM KV tier: the middle rung of the offload ladder.

The scorer's tier ladder is hbm(1.0) > host(0.8) > shared_storage(0.5)
(kvcache/scorer.py); this module supplies the middle tier the reference
ladder implies (backend.go:19-31 weighted gpu > cpu): offloaded block
groups stay resident in the pod's host RAM inside a byte-budgeted LRU,
so a re-admitted prefix pages back HBM<-DRAM without touching the
filesystem.  The shared-storage files remain the durable, cross-pod
medium underneath; the host tier is a per-pod read accelerator.

Thread-safe: the worker handlers insert from I/O completion threads
while the serving thread probes.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from llm_d_kv_cache_manager_tpu.utils import lockorder, victim
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("offload.host_tier")

DEFAULT_BUDGET_BYTES = 1 << 30  # 1 GiB


class HostTierCache:
    """file_hash -> block-major group bytes, LRU-evicted to a budget.

    ``on_evict(file_hash)`` fires (outside the lock) whenever the LRU
    drops an entry, so the pod can retract its ``host``-tier
    advertisement (a BlockRemoved event) and the fleet index stays
    truthful about DRAM residency."""

    def __init__(
        self,
        max_bytes: int = DEFAULT_BUDGET_BYTES,
        on_evict: Optional["callable"] = None,
        eviction_policy: Optional[object] = None,
    ) -> None:
        self.max_bytes = max_bytes
        self._on_evict = on_evict
        # Predictive eviction ranking (tiering/eviction.py): same
        # contract as CostAwareIndexConfig.eviction_policy — called
        # under our lock with an LRU-ordered (file_hash, nbytes)
        # sample, takes no locks of its own.  None = pristine
        # pop-LRU-first (the parity oracle).
        self._eviction_policy = eviction_policy
        self._entries: "OrderedDict[int, np.ndarray]" = OrderedDict()  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        # Leaf lock: on_evict deliberately fires OUTSIDE it, so no
        # other lock is ever acquired while this one is held.
        self._lock = lockorder.tracked(
            threading.Lock(), "HostTierCache._lock"
        )
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def put(self, file_hash: int, group: np.ndarray) -> bool:
        """Insert/refresh a group; oldest entries fall off the budget.

        Returns False when the group exceeds the whole budget (not
        admitted) — callers must not advertise it as host-resident."""
        nbytes = group.nbytes
        if nbytes > self.max_bytes:
            return False
        evicted_hashes = []
        with self._lock:
            old = self._entries.pop(file_hash, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[file_hash] = group
            self._bytes += nbytes
            policy = self._eviction_policy
            while self._bytes > self.max_bytes:
                if policy is None:
                    evicted_hash, evicted = self._entries.popitem(
                        last=False
                    )
                else:
                    evicted_hash = self._select_victim_locked(
                        policy, file_hash
                    )
                    evicted = self._entries.pop(evicted_hash)
                self._bytes -= evicted.nbytes
                evicted_hashes.append(evicted_hash)
        if self._on_evict is not None:
            for evicted_hash in evicted_hashes:
                self._on_evict(evicted_hash)
        return True

    def _select_victim_locked(self, policy, incoming_hash: int) -> int:
        """Predictive victim over an LRU-ordered sample; the group
        just inserted is never its own victim (the budget loop would
        livelock admitting and evicting the same entry).  The shared
        guard (utils/victim.py) bounds-checks the policy's answer and
        falls back to the LRU-first victim on any failure."""
        sample = []
        limit = victim.sample_limit(policy)
        for file_hash, group in self._entries.items():
            if file_hash == incoming_hash:
                continue
            sample.append((file_hash, group.nbytes))
            if len(sample) >= limit:
                break
        if not sample:
            # Only the incoming entry remains; it must go (same as the
            # pristine path when the budget cannot hold one group).
            return incoming_hash
        return sample[victim.guarded_select(policy, sample, logger)][0]

    def get(self, file_hash: int) -> Optional[np.ndarray]:
        """Fetch + refresh recency; None on miss."""
        with self._lock:
            group = self._entries.get(file_hash)
            if group is None:
                self.misses += 1
                return None
            self._entries.move_to_end(file_hash)
            self.hits += 1
            return group

    def contains(self, file_hash: int) -> bool:
        with self._lock:
            return file_hash in self._entries

    def lookup_consecutive(self, file_hashes: List[int]) -> int:
        """Length of the resident consecutive prefix (manager-side
        probe, mirroring the file-existence lookup)."""
        count = 0
        with self._lock:
            for file_hash in file_hashes:
                if file_hash not in self._entries:
                    break
                count += 1
        return count

    def evict(self, file_hash: int) -> bool:
        with self._lock:
            group = self._entries.pop(file_hash, None)
            if group is None:
                return False
            self._bytes -= group.nbytes
            return True

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
            }
