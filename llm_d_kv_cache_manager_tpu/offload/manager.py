"""Scheduler-side offload manager (runs on mesh-rank 0 only).

Decides what to load/store against shared storage by probing the file
layout — stateless, like the reference manager (kv_connectors/
llmd_fs_backend/llmd_fs_backend/manager.py:44-103): lookup counts
consecutive resident blocks from the start; stores are always accepted
(shared storage does its own eviction); loads need no preparation.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Iterable, List, Optional

from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper


@dataclass
class PrepareStoreOutput:
    block_hashes_to_store: List[int]
    block_hashes_evicted: List[int] = field(default_factory=list)


class SharedStorageOffloadManager:
    def __init__(self, file_mapper: FileMapper) -> None:
        self.file_mapper = file_mapper

    def lookup(self, block_hashes: Iterable[int]) -> int:
        """Consecutive-from-start resident block count."""
        hits = 0
        for block_hash in block_hashes:
            if not os.path.exists(self.file_mapper.get_file_name(block_hash)):
                break
            hits += 1
        return hits

    def prepare_load(self, block_hashes: Iterable[int]) -> List[int]:
        return list(block_hashes)

    def complete_load(self, block_hashes: Iterable[int]) -> None:
        pass

    def touch(self, block_hashes: Iterable[int]) -> None:
        # Recency refresh happens on the I/O threads during store-dedupe
        # (native engine touch path) to keep this scheduler call cheap.
        pass

    def prepare_store(
        self, block_hashes: Iterable[int]
    ) -> Optional[PrepareStoreOutput]:
        return PrepareStoreOutput(block_hashes_to_store=list(block_hashes))

    def complete_store(
        self, block_hashes: Iterable[int], success: bool = True
    ) -> None:
        pass
