"""Scheduler-side offload manager (runs on mesh-rank 0 only).

Decides what to load/store against shared storage by probing the file
layout — stateless, like the reference manager (kv_connectors/
llmd_fs_backend/llmd_fs_backend/manager.py:44-103): lookup counts
consecutive resident blocks from the start; stores are always accepted
(shared storage does its own eviction); loads need no preparation.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Iterable, List, Optional

from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper


@dataclass
class PrepareStoreOutput:
    block_hashes_to_store: List[int]
    block_hashes_evicted: List[int] = field(default_factory=list)


class SharedStorageOffloadManager:
    def __init__(
        self, file_mapper: FileMapper, full_file_nbytes: Optional[int] = None
    ) -> None:
        self.file_mapper = file_mapper
        # Bytes of a full block-group file.  When known, lookup demands
        # it: a smaller file is a partial (head) group whose tail blocks
        # are not resident, and promising it to the scheduler would make
        # the later load fail after the placement decision.
        self.full_file_nbytes = full_file_nbytes

    def lookup(self, block_hashes: Iterable[int]) -> int:
        """Consecutive-from-start resident block count."""
        hits = 0
        for block_hash in block_hashes:
            path = self.file_mapper.get_file_name(block_hash)
            try:
                size = os.path.getsize(path)
            except OSError:
                break
            if self.full_file_nbytes is not None and size < self.full_file_nbytes:
                break
            hits += 1
        return hits

    def prepare_load(self, block_hashes: Iterable[int]) -> List[int]:
        return list(block_hashes)

    def complete_load(self, block_hashes: Iterable[int]) -> None:
        pass

    def touch(self, block_hashes: Iterable[int]) -> None:
        """Refresh mtime so recency sweepers keep hot blocks.

        Load-heavy fleets never re-store a popular prefix, and reads
        don't move mtime (atime is dead on noatime mounts), so without
        this the hottest blocks look coldest.  Best-effort: a vanished
        file is simply skipped.
        """
        for block_hash in block_hashes:
            try:
                os.utime(self.file_mapper.get_file_name(block_hash))
            except OSError:
                pass

    def prepare_store(
        self, block_hashes: Iterable[int]
    ) -> Optional[PrepareStoreOutput]:
        return PrepareStoreOutput(block_hashes_to_store=list(block_hashes))

    def complete_store(
        self, block_hashes: Iterable[int], success: bool = True
    ) -> None:
        pass
