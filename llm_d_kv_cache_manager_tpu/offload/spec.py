"""Connector assembly: config -> manager + handlers + file mapper.

Parity with the reference's ``SharedStorageOffloadingSpec`` (kv_connectors/
llmd_fs_backend/llmd_fs_backend/spec.py:36-117): reads the connector
config, validates the offloaded-block geometry (offloaded block size must
be a whole multiple of the device block size), builds the FileMapper keyed
by model/geometry/mesh-axes/rank/dtype, and hands the scheduler a manager
(rank 0 only) and the workers their transfer handlers.

The mesh axes (tp/pp/pcp sizes and this worker's rank) come from the JAX
device mesh instead of torch.distributed world info.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import KVCachePool
from llm_d_kv_cache_manager_tpu.native.engine import OffloadEngine
from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper
from llm_d_kv_cache_manager_tpu.offload.manager import (
    SharedStorageOffloadManager,
)
from llm_d_kv_cache_manager_tpu.offload.staging import StagingBudget
from llm_d_kv_cache_manager_tpu.offload.staging_engine import (
    DEFAULT_LANE_WAIT_S,
    DEFAULT_SLOTS_PER_LANE,
    StagingConfig,
    StagingEngine,
)
from llm_d_kv_cache_manager_tpu.offload.worker import (
    DeviceToStorageHandler,
    StorageToDeviceHandler,
    StoreEventSink,
)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


@dataclass
class TPUOffloadSpec:
    """Connector configuration (the ``--kv-transfer-config`` analogue)."""

    shared_storage_path: str
    model_name: str
    # Tokens per device KV block.
    device_block_size: int = 16
    # Tokens per offloaded block (one file); must be a whole multiple of
    # device_block_size.
    offloaded_block_size: int = 64
    threads_per_chip: int = 4
    numa_node: int = -1
    # Host-DRAM tier budget; 0 disables the middle tier and offload
    # goes straight to shared storage (docs/architecture.md ladder).
    host_cache_bytes: int = 0
    # Cap on in-flight host staging bytes across both transfer
    # directions (reference clamps I/O threads against the same budget,
    # llmd_fs_backend/worker.py:191-216); submissions block until
    # completions free room.
    max_staging_memory_gb: float = 150.0
    # Per-chip staging-lane pipeline (offload/staging_engine.py,
    # docs/host-offload.md).  0 disables (the one-shot gather path, the
    # parity oracle); -1 resolves from OFFLOAD_STAGING_LANES (default
    # 0).  slots: pipeline depth per lane (-1 = OFFLOAD_STAGING_SLOTS,
    # default 2 = double buffering); lane_wait_s: saturation watchdog
    # (-1 = OFFLOAD_STAGING_WATCHDOG_S, default 60).
    staging_lanes: int = -1
    staging_slots: int = -1
    staging_lane_wait_s: float = -1.0
    dtype: str = "bfloat16"
    tp_size: int = 1
    pp_size: int = 1
    pcp_size: int = 1
    rank: int = 0

    def __post_init__(self) -> None:
        if self.offloaded_block_size % self.device_block_size != 0:
            raise ValueError(
                "offloaded_block_size must be a multiple of "
                f"device_block_size ({self.offloaded_block_size} % "
                f"{self.device_block_size} != 0)"
            )
        if self.staging_lanes < 0:
            self.staging_lanes = _env_int("OFFLOAD_STAGING_LANES", 0)
        if self.staging_slots < 0:
            self.staging_slots = _env_int(
                "OFFLOAD_STAGING_SLOTS", DEFAULT_SLOTS_PER_LANE
            )
        if self.staging_lane_wait_s < 0:
            self.staging_lane_wait_s = _env_float(
                "OFFLOAD_STAGING_WATCHDOG_S", DEFAULT_LANE_WAIT_S
            )

    @property
    def blocks_per_file(self) -> int:
        return self.offloaded_block_size // self.device_block_size


class TPUOffloadConnector:
    """One per worker process; scheduler rank additionally gets a manager."""

    def __init__(
        self,
        spec: TPUOffloadSpec,
        pool: KVCachePool,
        event_sink: Optional[StoreEventSink] = None,
        policy_engine=None,
    ) -> None:
        if pool.config.block_size != spec.device_block_size:
            raise ValueError(
                f"pool block_size {pool.config.block_size} != spec "
                f"device_block_size {spec.device_block_size}; the storage "
                "layout would advertise a geometry the files don't have"
            )
        if pool.config.dtype != spec.dtype:
            raise ValueError(
                f"pool dtype {pool.config.dtype!r} != spec dtype "
                f"{spec.dtype!r}"
            )
        if len(pool.kv.sharding.device_set) > 1:
            # Like the reference (one engine per rank over that rank's
            # GPU tensors), each mesh rank runs its own connector over a
            # single-device pool holding its KV shard, writing under its
            # own rank_<r> path.  A multi-device pool here would make
            # every rank gather and persist the full global array —
            # rank-layout corruption, not just waste.
            raise ValueError(
                "pool spans multiple devices; run one connector per "
                "mesh rank over that rank's local (single-device) pool "
                "and set spec.rank accordingly"
            )
        self.spec = spec
        self.pool = pool
        self.file_mapper = FileMapper(
            root_dir=spec.shared_storage_path,
            model_name=spec.model_name,
            device_block_size=spec.device_block_size,
            blocks_per_file=spec.blocks_per_file,
            tp_size=spec.tp_size,
            pp_size=spec.pp_size,
            pcp_size=spec.pcp_size,
            rank=spec.rank,
            dtype=spec.dtype,
        )
        self.engine = OffloadEngine(
            n_threads=spec.threads_per_chip, numa_node=spec.numa_node
        )
        self.staging_budget = StagingBudget(
            int(spec.max_staging_memory_gb * (1 << 30))
        )
        # Predictive tiering (tiering/engine.py): when attached, the
        # host tier evicts by predicted-next-use x byte-cost and every
        # load completion feeds the compute-or-load RTT estimator.
        self.policy_engine = policy_engine
        host_eviction_policy = None
        rtt_observer = None
        store_rtt_observer = None
        if policy_engine is not None:
            host_eviction_policy = policy_engine.eviction_policy(
                backend="host_tier"
            )
            rtt_observer = policy_engine.advisor.observe_load
            store_rtt_observer = policy_engine.advisor.observe_store
            if policy_engine.advisor.config.bytes_per_block <= 0:
                policy_engine.advisor.config.bytes_per_block = (
                    pool.block_nbytes
                )
        # Per-chip staging lanes (docs/host-offload.md): pinned-slot
        # pipeline overlapping device DMA with file I/O.  Off by
        # default — the one-shot path is the parity oracle.
        self.staging: Optional[StagingEngine] = None
        if spec.staging_lanes > 0:
            self.staging = StagingEngine(
                pool,
                self.engine,
                self.file_mapper,
                spec.blocks_per_file,
                StagingConfig(
                    lanes_per_chip=spec.staging_lanes,
                    slots_per_lane=spec.staging_slots,
                    lane_wait_s=spec.staging_lane_wait_s,
                ),
            )
        self.host_cache = None
        if spec.host_cache_bytes > 0:
            from llm_d_kv_cache_manager_tpu.offload.host_tier import (
                HostTierCache,
            )

            self.host_cache = HostTierCache(
                spec.host_cache_bytes,
                eviction_policy=host_eviction_policy,
            )
        self.store_handler = DeviceToStorageHandler(
            pool,
            self.engine,
            self.file_mapper,
            event_sink=event_sink,
            host_cache=self.host_cache,
            staging_budget=self.staging_budget,
            staging=self.staging,
            rtt_observer=store_rtt_observer,
        )
        self.load_handler = StorageToDeviceHandler(
            pool,
            self.engine,
            self.file_mapper,
            host_cache=self.host_cache,
            staging_budget=self.staging_budget,
            rtt_observer=rtt_observer,
            staging=self.staging,
        )

    def get_manager(self) -> SharedStorageOffloadManager:
        """Scheduler-side manager; call on mesh-rank 0 only."""
        return SharedStorageOffloadManager(
            self.file_mapper,
            full_file_nbytes=self.pool.block_nbytes
            * self.spec.blocks_per_file,
        )

    def get_finished(self):
        """Poll the shared engine once and route each completion to the
        handler that owns the job (store-event emission / load scatter
        happen here).  With staging enabled, engine completions are
        offered to the staging engine first — its sub-jobs never
        surface raw; the PARENT job id surfaces once its last file
        lands."""
        completions = []
        for job_id, status in self.engine.get_finished():
            if self.staging is not None and self.staging.claim(
                job_id, status
            ):
                continue  # a staged sub-job; parent surfaces below
            completions.append((job_id, status))
        if self.staging is not None:
            completions.extend(self.staging.pop_ready())
        routed = []
        for job_id, status in completions:
            for handler in (self.store_handler, self.load_handler):
                if handler.owns(job_id):
                    status = handler.on_finished(job_id, status)
                    break
            routed.append((job_id, status))
        return routed

    def close(self) -> None:
        self.engine.close()
