"""Host staging-memory budget for offload transfers.

The reference bounds staging memory by clamping I/O threads, since each
of its threads owns one pinned buffer (kv_connectors/llmd_fs_backend/
llmd_fs_backend/worker.py:191-216).  Our engine instead queues whole-job
host buffers, so the binding resource is *in-flight bytes*: every
submitted-but-unfinished job holds its gather/read buffers alive.  This
budget gates submissions on that total, blocking the submitter until
completions release enough bytes — backpressure, not OOM.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from llm_d_kv_cache_manager_tpu.utils import lockorder


class StagingBudget:
    """Byte-budget gate for in-flight host buffers.

    ``acquire`` blocks until the bytes fit (a single over-budget request
    is admitted alone rather than deadlocking); ``release`` returns bytes
    at job completion.  Thread-safe; waiters wake on every release.
    """

    def __init__(self, max_bytes: int) -> None:
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be positive, got {max_bytes}")
        self.max_bytes = max_bytes
        self._in_flight = 0  # guarded-by: _cond
        # Leaf of the offload lock hierarchy: waiters block here, but
        # nothing else is acquired while it is held.
        self._cond = lockorder.tracked(
            threading.Condition(), "StagingBudget._cond"
        )

    @property
    def in_flight_bytes(self) -> int:
        with self._cond:
            return self._in_flight

    def _fits_locked(self, nbytes: int) -> bool:
        if self._in_flight + nbytes <= self.max_bytes:
            return True
        # A request larger than the whole budget can never "fit"; admit
        # it alone rather than wedging the caller forever.
        return nbytes > self.max_bytes and self._in_flight == 0

    def acquire(self, nbytes: int, timeout: Optional[float] = None) -> bool:
        """Block until ``nbytes`` fit in the budget; True on success."""
        if nbytes < 0:
            raise ValueError(f"nbytes must be non-negative, got {nbytes}")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._fits_locked(nbytes):
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            self._in_flight += nbytes
            return True

    def try_acquire(self, nbytes: int) -> bool:
        """Non-blocking acquire: True iff the bytes fit right now.

        Submission paths that run on a serving thread must use this
        instead of ``acquire``: when releases can only happen via later
        calls on the *same* thread (e.g. vLLM's worker polls
        ``get_finished`` between ``transfer_async`` calls), a blocking
        acquire deadlocks the serving loop once in-flight bytes reach
        the budget.
        """
        return self.acquire(nbytes, timeout=0)

    def release(self, nbytes: int) -> None:
        with self._cond:
            self._in_flight -= nbytes
            if self._in_flight < 0:  # defensive: never go negative
                self._in_flight = 0
            self._cond.notify_all()
