"""Per-chip XLA host-offload staging engine: pinned lanes + slot pipeline.

The one-shot handlers (offload/worker.py) move a whole transfer as one
device gather + one DMA + one engine job.  That is simple and correct,
but it serializes the two halves of every job: the chip's DMA engine
idles while the I/O pool writes files, and the I/O pool idles while the
chip gathers.  This module is the reference's ``StorageOffloadEngine``
equivalent (SURVEY §2.2) rebuilt on XLA memory spaces: each chip owns a
fixed set of **lanes**, each lane a ring of reusable **staging slots**
sized to one block-major file group, and a transfer pipelines through
them —

    slot N:   device gather+transpose (XLA) -> pinned_host DMA
    slot N-1: file read/write on the native I/O pool

— so the device DMA for slot N overlaps the file I/O for slot N-1, the
way the reference overlaps ``cudaMemcpyAsync`` with its NUMA-pinned I/O
threads (storage_offload.cpp:145-239).  On backends with a
``pinned_host`` memory space (TPU) the DMA lands file-layout bytes
straight in pinned pages (the transpose happens on device,
models/kv_cache_pool.py); on backends without one the lane's slots are
plain reusable numpy buffers and the pipeline still holds (CPU parity
path, exercised by tests).

Contract with the shared :class:`~llm_d_kv_cache_manager_tpu.native.
engine.OffloadEngine`: the staging engine submits one engine **sub-job
per file group** from a reserved id range (``SUB_ID_BASE``), so
incremental submission never collides with connector-assigned job ids.
The connector's harvest loop offers every engine completion to
:meth:`claim` first; when a parent's last sub-job lands, the parent
surfaces through :meth:`pop_ready` (or :meth:`wait`) and the owning
handler finishes it exactly like a one-shot job — event emission,
metrics, and RTT stamping stay in offload/worker.py, byte movement
lives here.  Each staged job is harvested through EITHER the polling
path or :meth:`wait`, never both (the engine's own contract).

Atomicity: file writes ride the engine's tmp+rename path unchanged, and
the reference layout is untouched — GPU pods, TPU pods, one-shot pods
and staged pods all share one filesystem tree.

Backpressure (watchdog-armed): slot reuse waits for that slot's
previous sub-job via ``engine.wait`` (self-draining — no external
harvest needed, so a submitter blocked here always makes progress),
and lane acquisition times out with :class:`StagingSaturated` instead
of wedging a serving thread when every lane is stuck.  The
:class:`~llm_d_kv_cache_manager_tpu.offload.staging.StagingBudget`
composes safely on top: budget bytes are acquired before a lane, and
lanes free at end of submission without needing a harvest, so there is
no budget<->lane cycle (pinned by tests/test_staging_engine.py).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import KVCachePool
from llm_d_kv_cache_manager_tpu.native.engine import (
    JobStatus,
    OffloadEngine,
)
from llm_d_kv_cache_manager_tpu.obs.trace import span as obs_span
from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("offload.staging_engine")

# Engine sub-job ids live above every connector-assigned job id — far
# outside any realistic caller range (vLLM job ids are small ints).
SUB_ID_BASE = 1 << 48

DEFAULT_LANES_PER_CHIP = 2
DEFAULT_SLOTS_PER_LANE = 2
DEFAULT_LANE_WAIT_S = 60.0

# StagingEngine._cond is released around every engine call (store/load/
# wait) and around pool scatters; only _scatter_lock is a strict leaf.
# kvlint: lock-order: StagingEngine._cond ascending
lockorder.declare_ascending("StagingEngine._cond")
# kvlint: lock-order: StagingEngine._scatter_lock ascending
lockorder.declare_ascending("StagingEngine._scatter_lock")


class StagingSaturated(RuntimeError):
    """Every lane stayed busy past the watchdog window — the engine is
    wedged or oversubscribed; raised instead of deadlocking a serving
    thread."""


@dataclass
class StagingConfig:
    """Lane/slot geometry for one chip's staging engine.

    ``lanes_per_chip`` bounds concurrent pipelines per chip (one lane
    per in-flight transfer); ``slots_per_lane`` is the pipeline depth
    (2 = classic double buffering: one slot in device DMA while the
    other is in file I/O).  ``use_pinned=None`` probes the pool's
    device; ``False`` forces the CPU parity path."""

    lanes_per_chip: int = DEFAULT_LANES_PER_CHIP
    slots_per_lane: int = DEFAULT_SLOTS_PER_LANE
    lane_wait_s: float = DEFAULT_LANE_WAIT_S
    use_pinned: Optional[bool] = None

    def __post_init__(self) -> None:
        if self.lanes_per_chip <= 0:
            raise ValueError("lanes_per_chip must be positive")
        if self.slots_per_lane <= 0:
            raise ValueError("slots_per_lane must be positive")


class _Slot:
    """One reusable staging slot: holds the host buffer (and, on the
    pinned path, the pinned jax array keeping those pages alive) of at
    most one in-flight engine sub-job."""

    __slots__ = ("buffer", "sub_id", "pinned_ref")

    def __init__(self) -> None:
        self.buffer: Optional[np.ndarray] = None  # lazily allocated
        self.sub_id: Optional[int] = None  # outstanding occupant
        self.pinned_ref: Optional[object] = None


class _Lane:
    __slots__ = ("index", "slots", "cursor", "busy")

    def __init__(self, index: int, n_slots: int) -> None:
        self.index = index
        self.slots = [_Slot() for _ in range(n_slots)]
        self.cursor = 0
        self.busy = False  # guarded-by: StagingEngine._cond


@dataclass
class _Sub:
    """One engine sub-job (= one file group) of a staged parent."""

    parent_id: int
    status: Optional[JobStatus] = None
    waiter: bool = False  # a thread is inside engine.wait for this sub
    # Load-side scatter payload (None for stores / after scatter).
    block_ids: Optional[List[int]] = None
    buffer: Optional[np.ndarray] = None


@dataclass
class _Parent:
    direction: str  # "store" | "load"
    pending: set = field(default_factory=set)  # sub ids not yet complete
    submitted: bool = False
    failed: bool = False
    ready: bool = False
    files: int = 0
    file_nbytes: int = 0
    device_s: float = 0.0
    io_start: Optional[float] = None
    io_s: float = 0.0


# (file_hash, device_block_ids) — same shape as offload.worker's
# FileBlockGroup (redeclared: worker imports this module).
FileGroup = Tuple[int, Sequence[int]]


class StagingEngine:
    """Per-chip pinned staging lanes over the shared native I/O pool."""

    def __init__(
        self,
        pool: KVCachePool,
        engine: OffloadEngine,
        file_mapper: FileMapper,
        blocks_per_file: int,
        config: Optional[StagingConfig] = None,
    ) -> None:
        if blocks_per_file <= 0:
            raise ValueError("blocks_per_file must be positive")
        self.pool = pool
        self.engine = engine
        self.file_mapper = file_mapper
        self.blocks_per_file = blocks_per_file
        self.config = config or StagingConfig()
        self._use_pinned = (
            pool.pinned_host
            if self.config.use_pinned is None
            else bool(self.config.use_pinned)
        )
        self._lanes = [
            _Lane(i, self.config.slots_per_lane)
            for i in range(self.config.lanes_per_chip)
        ]
        self._cond = lockorder.tracked(
            threading.Condition(), "StagingEngine._cond"
        )
        self._parents: Dict[int, _Parent] = {}  # guarded-by: _cond
        self._subs: Dict[int, _Sub] = {}  # guarded-by: _cond
        self._ready: List[Tuple[int, JobStatus]] = []  # guarded-by: _cond
        self._sub_ids = itertools.count(SUB_ID_BASE)
        # Serializes pool.kv read-modify-write: scatters may run from
        # the lane-owner thread (slot retirement) and the connector's
        # harvest thread concurrently, and two overlapping
        # ``pool.kv = scatter(pool.kv, ...)`` calls would lose one.
        self._scatter_lock = lockorder.tracked(
            threading.Lock(), "StagingEngine._scatter_lock"
        )

    @property
    def uses_pinned(self) -> bool:
        """Whether the pinned_host DMA path is active (False = CPU
        parity path with plain reusable numpy slots)."""
        return self._use_pinned

    def scatter_block_major(self, block_ids, group) -> None:
        """Pool scatter serialized with this engine's harvest-time
        scatters (pool.kv is a read-modify-write; see _scatter_lock).
        Handlers route their host-tier-hit scatters through here."""
        with self._scatter_lock:
            self.pool.scatter_block_major(block_ids, group)

    # -- geometry ---------------------------------------------------------

    def _group_shape(self, n_blocks: int) -> Tuple[int, ...]:
        c = self.pool.config
        return (
            n_blocks,
            c.num_layers,
            2,
            c.block_size,
            c.num_kv_heads,
            c.head_dim,
        )

    def _slot_buffer(self, slot: _Slot) -> np.ndarray:
        """The slot's full-group reusable buffer (lazily allocated —
        lanes sized but never used cost nothing)."""
        if slot.buffer is None:
            from llm_d_kv_cache_manager_tpu.offload.worker import host_dtype

            slot.buffer = np.empty(
                self._group_shape(self.blocks_per_file),
                dtype=host_dtype(self.pool.config.dtype),
            )
        return slot.buffer

    # -- lane lifecycle ---------------------------------------------------

    def _acquire_lane(self) -> _Lane:
        deadline = time.monotonic() + self.config.lane_wait_s
        waited = False
        with self._cond:
            while True:
                for lane in self._lanes:
                    if not lane.busy:
                        lane.busy = True
                        return lane
                if not waited:
                    waited = True
                    METRICS.offload_staging_lane_waits.inc()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise StagingSaturated(
                        f"no staging lane freed within "
                        f"{self.config.lane_wait_s:.1f}s "
                        f"({len(self._lanes)} lanes, all busy)"
                    )
                self._cond.wait(min(remaining, 1.0))

    def _release_lane(self, lane: _Lane) -> None:
        with self._cond:
            lane.busy = False
            self._cond.notify_all()

    def _acquire_lane_for(self, parent_id: int, parent: _Parent) -> _Lane:
        """Lane acquisition for a registered parent: a watchdog raise
        must not strand the parent record — the job completes as
        FAILED (harvestable by poll or wait, releasing the caller's
        budget/pending state) before the exception surfaces."""
        try:
            return self._acquire_lane()
        except BaseException:
            with self._cond:
                parent.failed = True
                parent.submitted = True
                self._check_parent_locked(parent_id, parent)
            raise

    # -- sub-job completion machinery ------------------------------------

    def claim(self, job_id: int, status: JobStatus) -> bool:
        """Offer an engine completion; True iff it was a staged sub-job
        (the connector must then NOT route the raw id to a handler)."""
        with self._cond:
            if job_id not in self._subs:
                return False
        self._finish_sub(job_id, status)
        return True

    def pop_ready(self) -> List[Tuple[int, JobStatus]]:
        """Parents whose last sub-job has landed since the last call."""
        with self._cond:
            ready, self._ready = self._ready, []
            return ready

    def wait(self, parent_id: int) -> JobStatus:
        """Block until ``parent_id`` completes; single-harvester
        contract (don't mix with the polling path for the same job)."""
        while True:
            with self._cond:
                parent = self._parents.get(parent_id)
                if parent is None:
                    return JobStatus.UNKNOWN
                for i, (pid, status) in enumerate(self._ready):
                    if pid == parent_id:
                        del self._ready[i]
                        return status
                pending = next(iter(parent.pending), None)
                if pending is None:
                    # Submission still running (or completion racing
                    # into _ready): wait for a state change.
                    self._cond.wait(0.05)
                    continue
            self._await_sub(pending)

    def _await_sub(self, sub_id: int) -> None:
        """Drive (or wait out) one sub-job's completion."""
        with self._cond:
            while True:
                sub = self._subs.get(sub_id)
                if sub is None or sub.status is not None:
                    return
                if not sub.waiter:
                    sub.waiter = True
                    break
                self._cond.wait(0.05)
        status = self.engine.wait(sub_id)
        if status == JobStatus.UNKNOWN:
            # An external harvest (connector poll) raced us and owns
            # this completion; wait for its claim() to land.
            with self._cond:
                while True:
                    sub = self._subs.get(sub_id)
                    if sub is None or sub.status is not None:
                        return
                    self._cond.wait(0.05)
        self._finish_sub(sub_id, status)

    def _finish_sub(self, sub_id: int, status: JobStatus) -> None:
        """Record one sub completion; scatters load groups (outside
        ``_cond``) and completes the parent on the last sub."""
        with self._cond:
            sub = self._subs.get(sub_id)
            if sub is None or sub.status is not None:
                return  # already finished (idempotence guard)
            scatter = None
            if (
                status == JobStatus.SUCCEEDED
                and sub.block_ids is not None
                and sub.buffer is not None
            ):
                scatter = (sub.block_ids, sub.buffer)
        if scatter is not None:
            try:
                with self._scatter_lock:
                    self.pool.scatter_block_major(*scatter)
            except Exception:
                logger.exception(
                    "staged scatter failed for sub %d", sub_id
                )
                status = JobStatus.FAILED
        with self._cond:
            # Double-check shape: the scatter must run OUTSIDE _cond,
            # and this second acquisition re-validates via pop() — a
            # racing finisher gets None and bails.
            sub = self._subs.pop(sub_id, None)  # kvlint: atomic-ok
            if sub is None:
                return
            sub.status = status
            parent = self._parents.get(sub.parent_id)
            if parent is not None:
                parent.pending.discard(sub_id)
                if status != JobStatus.SUCCEEDED:
                    parent.failed = True
                self._check_parent_locked(sub.parent_id, parent)
            self._cond.notify_all()

    def _check_parent_locked(self, parent_id: int, parent: _Parent) -> None:
        if parent.ready or not parent.submitted or parent.pending:
            return
        parent.ready = True
        if parent.io_start is not None:
            parent.io_s = time.perf_counter() - parent.io_start
        self._ready.append(
            (
                parent_id,
                JobStatus.FAILED if parent.failed else JobStatus.SUCCEEDED,
            )
        )
        self._cond.notify_all()

    def _retire_slot(self, slot: _Slot) -> None:
        """Wait out the slot's previous occupant before reuse (the
        pipeline's self-draining backpressure)."""
        if slot.sub_id is None:
            return
        self._await_sub(slot.sub_id)
        slot.sub_id = None
        slot.pinned_ref = None

    def _register_parent(self, parent_id: int, direction: str) -> _Parent:
        with self._cond:
            if parent_id in self._parents:
                raise ValueError(
                    f"staged job id {parent_id} is still in flight; ids "
                    "must be unique until harvested"
                )
            parent = _Parent(direction)
            self._parents[parent_id] = parent
            return parent

    def job_stats(self, parent_id: int, pop: bool = True) -> Optional[dict]:
        """Measured splits of a completed parent: ``device_s`` (gather +
        DMA/copy wall time), ``io_s`` (first file submit -> last file
        completion), ``file_nbytes``, ``files``.  ``pop`` retires the
        record (call once, at finish)."""
        with self._cond:
            parent = self._parents.get(parent_id)
            if parent is None:
                return None
            stats = {
                "direction": parent.direction,
                "files": parent.files,
                "file_nbytes": parent.file_nbytes,
                "device_s": parent.device_s,
                "io_s": parent.io_s,
            }
            if pop:
                if not parent.ready:
                    # An unharvested parent must survive until its
                    # completion surfaces; popping early would strand
                    # sub completions against a missing record.
                    stats["incomplete"] = True
                    return stats
                del self._parents[parent_id]
            return stats

    # -- store pipeline ---------------------------------------------------

    def store(
        self,
        parent_id: int,
        groups: Sequence[FileGroup],
        on_group: Optional[Callable[[int, np.ndarray], None]] = None,
    ) -> None:
        """Pipelined device -> pinned-slot -> file store of block groups.

        Submits one engine file job per group through the acquired
        lane's slot ring and returns once every group is submitted
        (file I/O may still be in flight).  ``on_group(file_hash,
        buffer)`` fires after each group's bytes land in host memory —
        the host-tier admission hook; the buffer is only valid during
        the callback (slots are reused), copy to retain.
        """
        parent = self._register_parent(parent_id, "store")
        if not groups:
            with self._cond:
                parent.submitted = True
                self._check_parent_locked(parent_id, parent)
            return
        lane = self._acquire_lane_for(parent_id, parent)
        device_s = 0.0
        try:
            for file_hash, ids in groups:
                slot = lane.slots[lane.cursor]
                lane.cursor = (lane.cursor + 1) % len(lane.slots)
                self._retire_slot(slot)
                t0 = time.perf_counter()
                # Child of the handler's offload.stage span (flat span
                # model: dotted children attribute time inside a stage).
                with obs_span(
                    "offload.stage.dma", parent="offload.stage"
                ) as span:
                    host = self._stage_store_group(slot, list(ids))
                    span.set_attr("blocks", len(ids))
                device_s += time.perf_counter() - t0
                if on_group is not None:
                    on_group(file_hash, host)
                sub_id = next(self._sub_ids)
                path = self.file_mapper.get_file_name(file_hash)
                with self._cond:
                    parent.pending.add(sub_id)
                    parent.files += 1
                    parent.file_nbytes += host.nbytes
                    if parent.io_start is None:
                        parent.io_start = time.perf_counter()
                    self._subs[sub_id] = _Sub(parent_id=parent_id)
                slot.sub_id = sub_id
                # While the I/O pool writes this file, the next loop
                # iteration's gather+DMA proceeds — the overlap.
                self.engine.store(sub_id, [path], [host], skip_existing=True)
        except BaseException:
            with self._cond:
                parent.failed = True
            raise
        finally:
            with self._cond:
                parent.device_s = device_s
                parent.submitted = True
                self._check_parent_locked(parent_id, parent)
            self._release_lane(lane)

    def _stage_store_group(
        self, slot: _Slot, ids: List[int]
    ) -> np.ndarray:
        """Stage one group's bytes for its file write.  The store side
        produces a FRESH host array per group either way (the gather
        materializes one); the slot only tracks its lifetime — slot
        retirement still bounds in-flight group buffers per lane to
        ``slots_per_lane``, without a redundant copy into a reusable
        buffer (the preallocated slot buffer serves the load side)."""
        if self._use_pinned:
            try:
                pinned = self.pool.stage_gather_pinned(ids)
                host = np.asarray(pinned)
                # Keep the pinned pages alive until the file write is
                # harvested, in case the numpy view aliases them.
                slot.pinned_ref = pinned
                return host
            except Exception:
                logger.warning(
                    "pinned_host staging failed; falling back to plain "
                    "host transfers",
                    exc_info=True,
                )
                # gil-atomic: one-way degrade flag; False is absorbing
                self._use_pinned = False
        host = self.pool.gather_block_major(ids)
        slot.pinned_ref = host
        return host

    # -- load pipeline ----------------------------------------------------

    def load(self, parent_id: int, groups: Sequence[FileGroup]) -> None:
        """Pipelined file -> slot -> device load; each group scatters
        into the pool as soon as its file read lands (slot retirement
        or harvest), so the upload for group N overlaps the read for
        group N+1.  Zero-group jobs still surface through
        ``pop_ready``/``wait`` (parity with ``engine.load``)."""
        parent = self._register_parent(parent_id, "load")
        if not groups:
            with self._cond:
                parent.submitted = True
                self._check_parent_locked(parent_id, parent)
            return
        lane = self._acquire_lane_for(parent_id, parent)
        try:
            for file_hash, ids in groups:
                slot = lane.slots[lane.cursor]
                lane.cursor = (lane.cursor + 1) % len(lane.slots)
                self._retire_slot(slot)
                view = self._slot_buffer(slot)[: len(ids)]
                sub_id = next(self._sub_ids)
                path = self.file_mapper.get_file_name(file_hash)
                with self._cond:
                    parent.pending.add(sub_id)
                    parent.files += 1
                    parent.file_nbytes += view.nbytes
                    if parent.io_start is None:
                        parent.io_start = time.perf_counter()
                    self._subs[sub_id] = _Sub(
                        parent_id=parent_id,
                        block_ids=list(ids),
                        buffer=view,
                    )
                slot.sub_id = sub_id
                self.engine.load(sub_id, [path], [view])
        except BaseException:
            with self._cond:
                parent.failed = True
            raise
        finally:
            with self._cond:
                parent.submitted = True
                self._check_parent_locked(parent_id, parent)
            self._release_lane(lane)

    # -- status -----------------------------------------------------------

    def stats(self) -> dict:
        with self._cond:
            return {
                "lanes": len(self._lanes),
                "slots_per_lane": self.config.slots_per_lane,
                "use_pinned": self._use_pinned,
                "busy_lanes": sum(1 for lane in self._lanes if lane.busy),
                "in_flight_parents": len(self._parents),
                "in_flight_subs": len(self._subs),
            }
