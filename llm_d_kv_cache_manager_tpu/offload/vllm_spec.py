"""vLLM ``OffloadingSpec`` adapter: plug the TPU shared-storage connector
into a stock vLLM(-TPU) pod.

This is the product boundary the reference ships as ``llmd_fs_backend``
(kv_connectors/llmd_fs_backend/llmd_fs_backend/spec.py:36-117): a spec
class vLLM loads via ``--kv-transfer-config``::

    --kv-transfer-config '{
      "kv_connector": "OffloadingConnector",
      "kv_role": "kv_both",
      "kv_connector_extra_config": {
        "spec_name": "TPUSharedStorageOffloadingSpec",
        "spec_module_path": "llm_d_kv_cache_manager_tpu.offload.vllm_spec",
        "shared_storage_path": "/mnt/files-storage/kv-cache/",
        "block_size": 256,
        "threads_per_chip": 8,
        "max_staging_memory_gb": 16
      }
    }'

vLLM is soft-imported: without it this module still imports, the layout
probe and handlers are unit-testable against duck-typed stand-ins, and
only constructing the spec inside a real vLLM process requires the real
dependency.

Worker-side KV layout discovery mirrors the reference's synthetic-shape
probe (kv_connectors/llmd_fs_backend/llmd_fs_backend/worker.py:270-346):
ask each layer's attention backend for a reference shape with sentinel
dimensions, then classify the live tensor as cross-layer
(``[L, num_blocks, ...]``), standard (``[num_blocks, ...]``), or
split-KV (``[2, num_blocks, ...]``), honoring the backend's stride
order.  File grouping follows vLLM's convention: the FIRST group of a
transfer may be partial (worker.py:100-117) — unlike the in-repo
jax-native connector, whose tail-partial deviation documents why
(offload/worker.py); here vLLM's scheduler defines the contract.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from llm_d_kv_cache_manager_tpu.native.engine import (
    JobStatus,
    OffloadEngine,
)
from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper
from llm_d_kv_cache_manager_tpu.offload.manager import (
    SharedStorageOffloadManager,
)
from llm_d_kv_cache_manager_tpu.offload.staging import StagingBudget
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

# drain()/wait_for() hold the router lock across engine.get_finished(),
# whose fallback/buffer locks nest inside — the one cross-component
# nesting in the offload path, declared for both KV006 halves.
# kvlint: lock-order: CompletionRouter._lock < _PythonEngine._lock
lockorder.declare_order("CompletionRouter._lock", "_PythonEngine._lock")
# kvlint: lock-order: CompletionRouter._lock < OffloadEngine._buffers_lock
lockorder.declare_order(
    "CompletionRouter._lock", "OffloadEngine._buffers_lock"
)

logger = get_logger("offload.vllm_spec")

DEFAULT_MAX_THREADS_PER_CHIP = 64
DEFAULT_MAX_STAGING_MEMORY_GB = 150

# --- soft vLLM import ------------------------------------------------------

try:  # pragma: no cover - exercised only inside a real vLLM process
    from vllm.v1.kv_offload.abstract import (
        LoadStoreSpec as _LoadStoreSpec,
    )
    from vllm.v1.kv_offload.abstract import (
        OffloadingManager as _OffloadingManager,
    )
    from vllm.v1.kv_offload.abstract import (
        PrepareStoreOutput as _PrepareStoreOutput,
    )
    from vllm.v1.kv_offload.mediums import GPULoadStoreSpec
    from vllm.v1.kv_offload.spec import OffloadingSpec as _OffloadingSpec
    from vllm.v1.kv_offload.worker.worker import (
        OffloadingHandler as _OffloadingHandler,
    )

    HAVE_VLLM = True
except ImportError:  # duck-typed stand-ins keep the module importable
    HAVE_VLLM = False

    class _LoadStoreSpec:  # type: ignore[no-redef]
        pass

    class _OffloadingManager:  # type: ignore[no-redef]
        pass

    class _OffloadingSpec:  # type: ignore[no-redef]
        def __init__(self, vllm_config, kv_cache_config) -> None:
            self.vllm_config = vllm_config
            self.kv_cache_config = kv_cache_config

    class _OffloadingHandler:  # type: ignore[no-redef]
        pass

    class GPULoadStoreSpec(_LoadStoreSpec):  # type: ignore[no-redef]
        """Stand-in carrying device block ids (vLLM's GPU medium)."""

        def __init__(self, block_ids: Iterable[int]) -> None:
            self.block_ids = list(block_ids)

        @staticmethod
        def medium() -> str:
            return "GPU"

    class _PrepareStoreOutput:  # type: ignore[no-redef]
        def __init__(
            self,
            block_hashes_to_store,
            store_spec,
            block_hashes_evicted=(),
        ) -> None:
            self.block_hashes_to_store = list(block_hashes_to_store)
            self.store_spec = store_spec
            self.block_hashes_evicted = list(block_hashes_evicted)


class TPUSharedStorageLoadStoreSpec(_LoadStoreSpec):
    """Load/store target: block-hash-named files on shared storage."""

    def __init__(self, block_hashes: Iterable[int]) -> None:
        self.block_hashes = list(block_hashes)

    def __repr__(self) -> str:  # matches reference mediums.py
        return repr(self.block_hashes)

    @staticmethod
    def medium() -> str:
        return "SHARED_STORAGE"


# --- KV tensor layout probe ------------------------------------------------

_PROBE_BLOCKS = 1234
_PROBE_BLOCK_SIZE = 16
_PROBE_HEADS = 8
_PROBE_HEAD_SIZE = 256


class KVTensorView:
    """One ``[num_blocks, ...]``-leading tensor (a layer, or one of K/V).

    ``read``/``write`` move whole kernel blocks between the device tensor
    and numpy host memory, byte-preserving (bf16 travels as uint16 bit
    patterns through torch, which cannot view bf16 as numpy directly).
    """

    def __init__(self, tensor, name: str) -> None:
        self.tensor = tensor
        self.name = name

    @property
    def block_nbytes(self) -> int:
        t = self.tensor
        if hasattr(t, "element_size"):  # torch
            return t.stride(0) * t.element_size()
        item = t.dtype.itemsize if hasattr(t.dtype, "itemsize") else 2
        return int(np.prod(t.shape[1:])) * item

    def read(self, block_ids: Sequence[int]) -> np.ndarray:
        t = self.tensor
        if hasattr(t, "detach"):  # torch tensor
            import torch

            chunk = t[list(block_ids)].detach().cpu().contiguous()
            if chunk.dtype == torch.bfloat16:
                chunk = chunk.view(torch.uint16)
            return chunk.numpy()
        if isinstance(t, np.ndarray):
            return t[list(block_ids)]
        raise TypeError(
            f"unsupported KV tensor type {type(t)!r} for layer "
            f"{self.name!r}; jax-native serving should use the in-repo "
            "KVCachePool connector (offload/spec.py), which scatters "
            "through the pool instead of mutating arrays in place"
        )

    def write(self, block_ids: Sequence[int], data: np.ndarray) -> None:
        t = self.tensor
        if hasattr(t, "detach"):
            import torch

            host = torch.from_numpy(np.ascontiguousarray(data))
            if t.dtype == torch.bfloat16:
                host = host.view(torch.bfloat16)
            t[list(block_ids)] = host.to(t.device)
            return
        if isinstance(t, np.ndarray):
            t[list(block_ids)] = data
            return
        raise TypeError(
            f"unsupported KV tensor type {type(t)!r} for layer "
            f"{self.name!r}"
        )


def infer_kv_tensor_views(
    kv_caches: Dict[str, object],
    attn_backends: Dict[str, type],
) -> Tuple[List[KVTensorView], int]:
    """Classify each layer's KV-cache layout; return (views, kernel_bs).

    Covers the reference's three cases (worker.py:270-346): cross-layer
    tensors (extra leading layer dim), standard ``[num_blocks, ...]``,
    and split-KV ``[2, num_blocks, ...]`` (K and V become separate
    views).  A backend-provided stride order permutes the probe shape
    before the block-size dimension is located.
    """
    views: List[KVTensorView] = []
    kernel_block_size: Optional[int] = None

    for layer_name, tensor in kv_caches.items():
        shape = tuple(tensor.shape)
        backend = attn_backends[layer_name]
        test_shape = tuple(
            backend.get_kv_cache_shape(
                num_blocks=_PROBE_BLOCKS,
                block_size=_PROBE_BLOCK_SIZE,
                num_kv_heads=_PROBE_HEADS,
                head_size=_PROBE_HEAD_SIZE,
            )
        )

        split_k_and_v = False
        has_layers_dim = False
        if len(shape) != len(test_shape):
            if len(shape) != len(test_shape) + 1:
                raise ValueError(
                    f"layer {layer_name!r}: tensor rank {len(shape)} "
                    f"does not match backend shape rank {len(test_shape)}"
                    " (+1 for cross-layer)"
                )
            has_layers_dim = True
            test_shape = (80,) + test_shape  # dummy layer count
        elif test_shape[0] == _PROBE_BLOCKS:
            pass  # standard [num_blocks, ...]
        else:
            if test_shape[0] != 2 or test_shape[1] != _PROBE_BLOCKS:
                raise ValueError(
                    f"layer {layer_name!r}: unrecognized KV layout "
                    f"{test_shape} for tensor shape {shape}"
                )
            if shape[0] != 2:
                raise ValueError(
                    f"layer {layer_name!r}: backend advertises split-KV "
                    f"but tensor leading dim is {shape[0]}, not 2"
                )
            split_k_and_v = True

        if split_k_and_v:
            views.append(KVTensorView(tensor[0], f"{layer_name}.k"))
            views.append(KVTensorView(tensor[1], f"{layer_name}.v"))
        else:
            views.append(KVTensorView(tensor, layer_name))

        try:
            stride_order = tuple(
                backend.get_kv_cache_stride_order(
                    include_num_layers_dimension=has_layers_dim
                )
            )
            if len(stride_order) != len(shape):
                raise ValueError(
                    f"layer {layer_name!r}: stride order length "
                    f"{len(stride_order)} != tensor rank {len(shape)}"
                )
        except (AttributeError, NotImplementedError, TypeError):
            stride_order = tuple(range(len(shape)))
        permuted = tuple(test_shape[i] for i in stride_order)

        block_size_idx = permuted.index(_PROBE_BLOCK_SIZE)
        layer_kernel_bs = shape[block_size_idx]
        if kernel_block_size is None:
            kernel_block_size = layer_kernel_bs
        elif kernel_block_size != layer_kernel_bs:
            raise ValueError(
                f"layer {layer_name!r}: kernel block size "
                f"{layer_kernel_bs} != {kernel_block_size} of earlier "
                "layers"
            )

    if not views or kernel_block_size is None:
        raise ValueError("no KV-cache tensors to offload")
    block_strides = {view.block_nbytes for view in views}
    if len(block_strides) != 1:
        raise ValueError(
            f"KV-cache tensors disagree on per-block bytes: {block_strides}"
        )
    return views, kernel_block_size


# --- worker-side handlers --------------------------------------------------


def build_file_block_mapping(
    file_mapper: FileMapper,
    block_hashes: Sequence[int],
    block_ids: Sequence[int],
    blocks_per_file: int,
) -> Tuple[List[str], List[List[int]]]:
    """vLLM grouping convention: the FIRST group may be partial
    (reference worker.py:100-117)."""
    files: List[str] = []
    per_file: List[List[int]] = []
    first = len(block_ids) % blocks_per_file or blocks_per_file
    start, size = 0, first
    for block_hash in block_hashes:
        end = min(start + size, len(block_ids))
        files.append(file_mapper.get_file_name(block_hash))
        per_file.append(list(block_ids[start:end]))
        start += size
        size = blocks_per_file
    return files, per_file


class CompletionRouter:
    """Routes a shared engine's completions to the owning handler.

    vLLM's ``OffloadingWorker`` polls ``get_finished`` on *every*
    handler, but the engine is shared by both directions — an unfiltered
    drain would let the store handler consume a load job's completion,
    so the load's harvest-time scatter never runs (silent KV corruption)
    and its staging bytes leak.  The in-repo jax-native connector routes
    via ``owns()``/``on_finished`` (offload/spec.py) for the same
    reason; this router is the vLLM-adapter equivalent: completions not
    owned by the draining handler are buffered until their owner drains.
    """

    def __init__(self, engine: OffloadEngine) -> None:
        self.engine = engine
        self._unclaimed: Dict[int, JobStatus] = {}  # guarded-by: _lock
        self._lock = lockorder.tracked(
            threading.Lock(), "CompletionRouter._lock"
        )

    def drain(self, owned_ids) -> List[Tuple[int, JobStatus]]:
        """Harvest engine completions; return only those in ``owned_ids``."""
        with self._lock:
            for job_id, status in self.engine.get_finished():
                self._unclaimed[job_id] = status
            mine = [j for j in list(self._unclaimed) if j in owned_ids]
            return [(j, self._unclaimed.pop(j)) for j in mine]

    def wait_for(self, job_id: int) -> JobStatus:
        """Block until ``job_id`` completes, wherever it was harvested.

        Held under the router lock so a completion can never sit
        popped-from-the-engine but not-yet-buffered while a waiter looks
        for it.  vLLM drives both handlers from one worker thread, so
        the lock is uncontended in practice.
        """
        with self._lock:
            if job_id in self._unclaimed:
                return self._unclaimed.pop(job_id)
            return self.engine.wait(job_id)


class _VllmHandlerBase(_OffloadingHandler):
    """Gathers/scatters whole device blocks through the native engine.

    One engine and one staging budget are shared by both directions; the
    :class:`CompletionRouter` keys completions to the handler whose
    ``_job_bytes`` holds the job id, so each direction's ``_finish``
    (budget release, and the load path's scatter) always runs.
    """

    def __init__(
        self,
        views: List[KVTensorView],
        kernel_blocks_per_block: int,
        blocks_per_file: int,
        file_mapper: FileMapper,
        engine: OffloadEngine,
        budget: StagingBudget,
        router: CompletionRouter,
    ) -> None:
        self.views = views
        self.kernel_blocks_per_block = kernel_blocks_per_block
        self.blocks_per_file = blocks_per_file
        self.file_mapper = file_mapper
        self.engine = engine
        self.budget = budget
        # Required, never defaulted: two handlers on one engine with
        # separate routers would strand each other's completions.
        self.router = router
        self._job_bytes: Dict[int, int] = {}
        # Probe once: host dtype and per-kernel-block element count.
        probe = views[0].read([0])
        self.host_dtype = probe.dtype
        self.kernel_block_elems = int(np.prod(probe.shape[1:]))

    def _kernel_ids(self, block_ids: Sequence[int]) -> List[int]:
        k = self.kernel_blocks_per_block
        return [b * k + j for b in block_ids for j in range(k)]

    def _file_buffer_shape(self, n_blocks: int) -> Tuple[int, ...]:
        """Block-major: per device block, every view's kernel blocks
        contiguous (flattened — views may differ in trailing shape but
        agree on bytes) — head-of-file bytes are the first blocks, so
        partial files are coherent prefixes."""
        return (
            n_blocks,
            len(self.views),
            self.kernel_blocks_per_block,
            self.kernel_block_elems,
        )

    def _job_nbytes(self, per_file: Sequence[Sequence[int]]) -> int:
        """Host bytes a job's file buffers will occupy (shape-derived, so
        it can be charged to the budget BEFORE any allocation)."""
        total = sum(
            int(np.prod(self._file_buffer_shape(len(ids))))
            for ids in per_file
        )
        return total * self.host_dtype.itemsize

    def get_finished(self) -> List[Tuple[int, bool]]:
        out = []
        for job_id, status in self.router.drain(self._job_bytes):
            out.append((job_id, self._finish(job_id, status)))
        return out

    def wait(self, job_ids) -> None:
        for job_id in set(job_ids):
            self._finish(job_id, self.router.wait_for(job_id))

    def _finish(self, job_id: int, status: JobStatus) -> bool:
        nbytes = self._job_bytes.pop(job_id, 0)
        if nbytes:
            self.budget.release(nbytes)
        return status == JobStatus.SUCCEEDED


class TPUToStorageHandler(_VllmHandlerBase):
    """Device -> shared-storage (PUT)."""

    def transfer_async(self, job_id: int, spec) -> bool:
        src, dst = spec
        files, per_file = build_file_block_mapping(
            self.file_mapper,
            dst.block_hashes,
            list(src.block_ids),
            self.blocks_per_file,
        )
        nbytes = self._job_nbytes(per_file)
        # Non-blocking: releases happen when this same vLLM worker thread
        # later polls get_finished, so blocking here would deadlock the
        # serving loop.  False tells vLLM to retry the transfer later.
        if not self.budget.try_acquire(nbytes):
            return False
        buffers = []
        for ids in per_file:
            stacked = np.stack(
                [
                    view.read(self._kernel_ids(ids)).reshape(
                        len(ids),
                        self.kernel_blocks_per_block,
                        self.kernel_block_elems,
                    )
                    for view in self.views
                ],
                axis=1,
            )
            buffers.append(np.ascontiguousarray(stacked))
        self._job_bytes[job_id] = nbytes
        self.engine.store(job_id, files, buffers, skip_existing=True)
        return True


class StorageToTPUHandler(_VllmHandlerBase):
    """Shared-storage -> device (GET).

    The scatter into the live KV tensors must wait for the file bytes, so
    it happens at harvest time (``get_finished``/``wait``), keeping the
    serving step free of blocking I/O.
    """

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        # job_id -> (per-file block ids, host buffers to scatter)
        self._pending: Dict[int, Tuple[List[List[int]], List[np.ndarray]]] = {}

    def transfer_async(self, job_id: int, spec) -> bool:
        src, dst = spec
        files, per_file = build_file_block_mapping(
            self.file_mapper,
            src.block_hashes,
            list(dst.block_ids),
            self.blocks_per_file,
        )
        # Acquire BEFORE allocating (mirrors the store path): a submitter
        # blocked-out by the budget must not already hold its job's host
        # memory, or the gate no longer bounds resident bytes.  And
        # non-blocking, for the same serving-loop-deadlock reason as the
        # store path.
        nbytes = self._job_nbytes(per_file)
        if not self.budget.try_acquire(nbytes):
            return False
        buffers = [
            np.empty(self._file_buffer_shape(len(ids)), dtype=self.host_dtype)
            for ids in per_file
        ]
        self._job_bytes[job_id] = nbytes
        self._pending[job_id] = (per_file, buffers)
        self.engine.load(job_id, files, buffers)
        return True

    def _finish(self, job_id: int, status: JobStatus) -> bool:
        ok = super()._finish(job_id, status)
        pending = self._pending.pop(job_id, None)
        if pending is None or not ok:
            return ok
        per_file, buffers = pending
        for ids, buffer in zip(per_file, buffers):
            kernel_ids = self._kernel_ids(ids)
            for view_idx, view in enumerate(self.views):
                data = buffer[:, view_idx].reshape(
                    len(kernel_ids), *view.tensor.shape[1:]
                )
                view.write(kernel_ids, data)
        return ok


# --- scheduler-side manager adapter ---------------------------------------


class TPUSharedStorageOffloadingManager(_OffloadingManager):
    """vLLM ``OffloadingManager`` facade over the shared-FS manager."""

    def __init__(self, file_mapper: FileMapper) -> None:
        self._inner = SharedStorageOffloadManager(file_mapper)

    def lookup(self, block_hashes: Iterable[int]) -> int:
        return self._inner.lookup(block_hashes)

    def prepare_load(self, block_hashes: Iterable[int]):
        return TPUSharedStorageLoadStoreSpec(block_hashes)

    def touch(self, block_hashes: Iterable[int]) -> None:
        self._inner.touch(block_hashes)

    def complete_load(self, block_hashes: Iterable[int]) -> None:
        pass

    def prepare_store(self, block_hashes: Iterable[int]):
        hashes = list(block_hashes)
        return _PrepareStoreOutput(
            block_hashes_to_store=hashes,
            store_spec=TPUSharedStorageLoadStoreSpec(hashes),
            block_hashes_evicted=[],
        )

    def complete_store(
        self, block_hashes: Iterable[int], success: bool = True
    ) -> None:
        pass


# --- the spec itself -------------------------------------------------------


class TPUSharedStorageOffloadingSpec(_OffloadingSpec):
    """Drop-in ``OffloadingSpec`` for vLLM(-TPU) pods.

    Reference parity: kv_connectors/llmd_fs_backend/llmd_fs_backend/
    spec.py:36-117, with the CUDA staging engine replaced by the TPU
    connector's native host-I/O engine and an in-flight staging-byte
    budget replacing the pinned-buffer thread clamp.
    """

    def __init__(self, vllm_config, kv_cache_config) -> None:
        super().__init__(vllm_config, kv_cache_config)
        self.vllm_config = vllm_config
        self.kv_cache_config = kv_cache_config

        extra = self._extra_config(vllm_config)
        self.threads_per_chip = int(
            extra.get(
                "threads_per_chip",
                extra.get("threads_per_gpu", DEFAULT_MAX_THREADS_PER_CHIP),
            )
        )
        self.shared_storage_path = extra.get(
            "shared_storage_path", "/tmp/shared-kv"
        )
        self.max_staging_memory_gb = float(
            extra.get("max_staging_memory_gb", DEFAULT_MAX_STAGING_MEMORY_GB)
        )

        self.device_block_size = int(vllm_config.cache_config.block_size)
        self.offloaded_block_size = int(
            extra.get("block_size", self.device_block_size)
        )
        if self.offloaded_block_size % self.device_block_size != 0:
            raise ValueError(
                "offloaded block_size must be a multiple of the device "
                f"block size ({self.offloaded_block_size} % "
                f"{self.device_block_size} != 0)"
            )
        self.blocks_per_file = (
            self.offloaded_block_size // self.device_block_size
        )

        parallel = vllm_config.parallel_config
        tp_size = int(getattr(parallel, "tensor_parallel_size", 1))
        pp_size = int(getattr(parallel, "pipeline_parallel_size", 1))
        pcp_size = int(
            getattr(parallel, "prefill_context_parallel_size", 1)
        )
        world = int(getattr(parallel, "world_size", tp_size * pp_size))
        if world != tp_size * pp_size * pcp_size:
            raise ValueError(
                f"world_size {world} != tp {tp_size} * pp {pp_size} * "
                f"pcp {pcp_size}"
            )

        dtype = str(vllm_config.cache_config.cache_dtype)
        if dtype in ("auto", "None"):
            dtype = str(getattr(vllm_config.model_config, "dtype", "auto"))
        dtype = dtype.replace("torch.", "")

        self.file_mapper = FileMapper(
            root_dir=self.shared_storage_path,
            model_name=vllm_config.model_config.model,
            device_block_size=self.device_block_size,
            blocks_per_file=self.blocks_per_file,
            tp_size=tp_size,
            pp_size=pp_size,
            pcp_size=pcp_size,
            rank=int(getattr(parallel, "rank", 0)),
            dtype=dtype,
        )
        self._manager: Optional[TPUSharedStorageOffloadingManager] = None
        self._handlers: Optional[
            Tuple[TPUToStorageHandler, StorageToTPUHandler]
        ] = None
        # Exact host bytes of one full file buffer, set by the handler
        # build (the staging clamp's unit; docs/configuration.md §8).
        self.file_buffer_nbytes: Optional[int] = None

    @staticmethod
    def _extra_config(vllm_config) -> dict:
        transfer = getattr(vllm_config, "kv_transfer_config", None)
        return dict(
            getattr(transfer, "kv_connector_extra_config", None) or {}
        )

    def get_manager(self) -> TPUSharedStorageOffloadingManager:
        rank = int(getattr(self.vllm_config.parallel_config, "rank", 0))
        if rank != 0:
            raise RuntimeError("scheduler-side manager runs on rank 0 only")
        if self._manager is None:
            self._manager = TPUSharedStorageOffloadingManager(
                self.file_mapper
            )
        return self._manager

    def get_handlers(self, kv_caches, attn_backends):
        """Yield (src medium, dst medium, handler) for both directions."""
        if self._handlers is None:
            self._handlers = self._build_handlers(kv_caches, attn_backends)
        store, load = self._handlers
        yield GPULoadStoreSpec, TPUSharedStorageLoadStoreSpec, store
        yield TPUSharedStorageLoadStoreSpec, GPULoadStoreSpec, load

    def _build_handlers(self, kv_caches, attn_backends):
        views, kernel_block_size = infer_kv_tensor_views(
            kv_caches, attn_backends
        )
        if self.device_block_size % kernel_block_size != 0:
            raise ValueError(
                f"device block size {self.device_block_size} is not a "
                f"multiple of kernel block size {kernel_block_size}"
            )
        kernel_per_block = self.device_block_size // kernel_block_size

        # Staging-budget sizing semantics (docs/configuration.md §8,
        # decided in the tiering PR, retiring the seed xfail): the
        # thread clamp and the runtime budget both count the EXACT
        # host bytes of one full block-major file buffer —
        # blocks_per_file x kernel_blocks_per_block x the sum of every
        # view's per-kernel-block bytes — the same number
        # ``_job_nbytes`` charges per file at submit time.  The seed
        # test's nominal "16KB per file" figure double-counted K/V and
        # dtype width; nominal figures drift, the allocated buffer
        # cannot.  Each I/O thread stages at most one file buffer, so
        # threads clamp to max(1, budget // file_buffer_nbytes).
        file_bytes = (
            sum(view.block_nbytes for view in views)
            * kernel_per_block
            * self.blocks_per_file
        )
        self.file_buffer_nbytes = file_bytes
        budget_bytes = int(self.max_staging_memory_gb * (1 << 30))
        threads = min(
            self.threads_per_chip,
            os.cpu_count() or 1,
            DEFAULT_MAX_THREADS_PER_CHIP,
        )
        if file_bytes * threads > budget_bytes:
            threads = max(1, budget_bytes // file_bytes)
            logger.warning(
                "clamped I/O threads to %d: file buffer %d bytes x "
                "threads exceeds max_staging_memory_gb=%.1f",
                threads,
                file_bytes,
                self.max_staging_memory_gb,
            )
        engine = OffloadEngine(n_threads=int(threads))
        budget = StagingBudget(budget_bytes)
        router = CompletionRouter(engine)  # shared: one drain point
        common = (
            views,
            kernel_per_block,
            self.blocks_per_file,
            self.file_mapper,
            engine,
            budget,
            router,
        )
        logger.info(
            "vLLM offload handlers: %d views, kernel_bs=%d, "
            "blocks_per_file=%d, threads=%d, staging=%.1fGB",
            len(views),
            kernel_block_size,
            self.blocks_per_file,
            threads,
            self.max_staging_memory_gb,
        )
        return TPUToStorageHandler(*common), StorageToTPUHandler(*common)
