"""Worker-side offload handlers: TPU HBM <-> shared storage.

The store path is *one device gather + one DMA + async file fanout*: the
handler gathers every requested block (all layers at once) into a single
contiguous host array, slices per-file views, and hands them to the native
I/O engine — replacing the reference's per-block-per-layer
``cudaMemcpyAsync`` loop + CUDA-event fencing (storage_offload.cpp:145-239,
tensor_copier.cu:50-97) with XLA's DMA engine.

The load path is the mirror: async file reads into host buffers, then on
completion one upload + jitted scatter into the cache pool.  Because the
scatter must wait for the file bytes, loads finish at harvest time
(``get_finished``/``wait``), keeping the serving step free of blocking I/O.

File grouping: an offloaded block = ``blocks_per_file`` device blocks; the
*last* file of a transfer may carry fewer (a partial tail group).  The
reference puts the partial group first (worker.py:100-117); we deviate
deliberately — prefix chains grow at the tail, and a tail-partial split is
the only one coherent with head-of-file bytes (see layout below).

File byte layout is **block-major**: ``[k, num_layers, 2, block_size,
heads, dim]`` — each block's all-layer data contiguous, matching the
reference's staging layout (tensor_copier.cu:50-97).  This is what makes
partial groups coherent: the head ``k * block_nbytes`` bytes of a file
ARE its first k blocks, so a partial store writes a valid prefix and a
partial load reads one.  The pool's device layout is layer-major (one
gather for all layers), so the host path transposes at the boundary.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import ml_dtypes  # ships with jax; registers bfloat16 as a numpy dtype
import numpy as np

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.obs.trace import (
    TRACER,
    span as obs_span,
    use_trace,
)
from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import KVCachePool
from llm_d_kv_cache_manager_tpu.native.engine import (
    JobStatus,
    OffloadEngine,
)
from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("offload.worker")


def host_dtype(name: str) -> np.dtype:
    """Numpy dtype for host staging buffers, incl. bf16 via ml_dtypes."""
    if name == "bfloat16":
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)

# (file_hash, device_block_ids) — one file per offloaded block group.
FileBlockGroup = Tuple[int, Sequence[int]]

# Called with (file_hashes, medium) when a store job lands, so the pod can
# advertise the new tier in its KVEvents stream.
StoreEventSink = Callable[[List[int], str], None]

# Write-side cost feed: (file_nbytes, io_seconds, device_seconds) per
# successful store job — the advisor's observe_store signature.
StoreRttObserver = Callable[[int, float, Optional[float]], None]

SHARED_STORAGE_MEDIUM = "shared_storage"
HOST_MEDIUM = "host"


def group_blocks_per_file(
    file_hashes: Sequence[int],
    block_ids: Sequence[int],
    blocks_per_file: int,
) -> List[FileBlockGroup]:
    """Group device block ids under their file hashes.

    The LAST group may be partial; earlier groups are full.  This is the
    prefix-caching shape — block chains grow at the tail, so a transfer
    covers whole groups from its start and at most one incomplete tail
    group — and it is what keeps partial files coherent with the
    head-of-file byte layout (module docstring): a partial group's k
    blocks are the first k of its group, stored at/loaded from the head
    of that group's file.  A tail-only store (resuming mid-group) cannot
    be expressed; re-store the whole group — size-aware dedupe makes the
    full rewrite upgrade the partial file.
    """
    if not file_hashes:
        return []
    remainder = len(block_ids) - (len(file_hashes) - 1) * blocks_per_file
    if remainder <= 0 or remainder > blocks_per_file:
        raise ValueError(
            f"{len(block_ids)} blocks cannot split into {len(file_hashes)} "
            f"files of up to {blocks_per_file}"
        )
    groups: List[FileBlockGroup] = []
    cursor = 0
    last = len(file_hashes) - 1
    for i, file_hash in enumerate(file_hashes):
        take = remainder if i == last else blocks_per_file
        groups.append((file_hash, list(block_ids[cursor : cursor + take])))
        cursor += take
    return groups


class _HandlerBase:
    """Shared-engine handler.

    Both handlers submit jobs to one engine, so raw ``engine.get_finished``
    interleaves their completions; each handler claims only its own job ids
    via ``owns``/``on_finished``, and the connector routes the harvest.
    Job ids must be unique across the connector.
    """

    def __init__(
        self,
        pool: KVCachePool,
        engine: OffloadEngine,
        file_mapper: FileMapper,
        staging_budget=None,
        staging=None,
    ) -> None:
        self.pool = pool
        self.engine = engine
        self.file_mapper = file_mapper
        # Optional in-flight host-byte gate (offload/staging.py); job
        # bytes are acquired before buffers exist and released at
        # completion, success or not.
        self._budget = staging_budget
        self._budget_bytes: Dict[int, int] = {}
        # Optional per-chip staging engine (offload/staging_engine.py):
        # when present, transfers pipeline through pinned lane slots
        # instead of the one-shot gather, and jobs in _staged complete
        # through the staging engine rather than raw engine ids.
        self._staging = staging
        self._staged: set = set()
        # Sampled per-job traces: job_id -> (trace, io-start stamp).
        # Submit-to-harvest, same single-submitter discipline as the
        # other per-job dicts here.
        self._job_traces: Dict[int, Tuple[object, float]] = {}
        # Unconditional io-start stamps (every job, traced or not):
        # the compute-or-load advisor's RTT estimator needs real
        # submit->harvest latencies, not just the sampled ones.
        self._io_started: Dict[int, float] = {}

    def _trace_submit(self, name: str, job_id: int, n_blocks: int):
        """Sampled trace for one offload job; None when unsampled."""
        job_trace = TRACER.start_trace(name)
        if job_trace is not None:
            job_trace.set_attr("job_id", job_id)
            job_trace.set_attr("blocks", n_blocks)
        return job_trace

    def _trace_io_start(self, job_id: int, job_trace) -> None:
        now = time.perf_counter()
        self._io_started[job_id] = now
        if job_trace is not None:
            self._job_traces[job_id] = (job_trace, now)

    def _io_elapsed(self, job_id: int) -> Optional[float]:
        """Submit->harvest seconds for a completing job (None for
        unknown jobs); call exactly once per completion."""
        started = self._io_started.pop(job_id, None)
        if started is None:
            return None
        return time.perf_counter() - started

    def _trace_finish(self, job_id: int, status: JobStatus) -> None:
        """Close the job's io span at harvest.  The io span covers
        engine submit -> completion harvest: actual file/DMA time plus
        any idle-until-harvest slack, which is exactly the latency the
        serving step experiences."""
        entry = self._job_traces.pop(job_id, None)
        if entry is None:
            return
        job_trace, io_start = entry
        job_trace.add_completed("offload.io", io_start)
        job_trace.set_attr("status", status.name.lower())
        job_trace.finish(
            "ok" if status == JobStatus.SUCCEEDED else "error"
        )

    def _budget_acquire(self, job_id: int, nbytes: int) -> None:
        if self._budget is not None and nbytes > 0:
            self._budget.acquire(nbytes)
            self._budget_bytes[job_id] = nbytes

    def _budget_release(self, job_id: int) -> None:
        if self._budget is not None:
            nbytes = self._budget_bytes.pop(job_id, 0)
            if nbytes:
                self._budget.release(nbytes)

    def owns(self, job_id: int) -> bool:
        raise NotImplementedError

    def on_finished(self, job_id: int, status: JobStatus) -> JobStatus:
        """Completion hook; returns the (possibly updated) status."""
        raise NotImplementedError

    def _staging_stats(self, job_id: int) -> Optional[dict]:
        """Measured splits of a completing staged job (pops the staging
        record); None for one-shot jobs."""
        if self._staging is None or job_id not in self._staged:
            return None
        self._staged.discard(job_id)
        return self._staging.job_stats(job_id)

    def wait(self, job_id: int) -> JobStatus:
        if self._staging is not None and job_id in self._staged:
            return self.on_finished(job_id, self._staging.wait(job_id))
        return self.on_finished(job_id, self.engine.wait(job_id))


class DeviceToStorageHandler(_HandlerBase):
    """Asynchronously persist device blocks to shared storage.

    With a ``host_cache``, gathered groups also stay resident in host
    DRAM (the middle tier) and a ``host``-medium event fires
    immediately — the durable ``shared_storage`` event follows when the
    file write lands."""

    def __init__(
        self,
        *args,
        event_sink: Optional[StoreEventSink] = None,
        host_cache=None,
        staging_budget=None,
        staging=None,
        rtt_observer: Optional[StoreRttObserver] = None,
    ):
        super().__init__(*args, staging_budget=staging_budget,
                         staging=staging)
        self._event_sink = event_sink
        self._host_cache = host_cache
        # Write-side advisor feed (tiering/advisor.py observe_store):
        # called with (file bytes, io seconds, device seconds) on every
        # successful store so demotion cost is priced from measurement.
        self._rtt_observer = rtt_observer
        # job_id -> (file hashes, payload bytes, device-transfer
        # seconds) until completion.
        self._job_hashes: Dict[
            int, Tuple[List[int], int, Optional[float]]
        ] = {}

    def transfer_async(
        self, job_id: int, groups: Sequence[FileBlockGroup]
    ) -> None:
        all_ids: List[int] = []
        for _, ids in groups:
            all_ids.extend(ids)
        job_trace = self._trace_submit("offload.store", job_id, len(all_ids))
        # Gate on the staging budget before the gather allocates.
        self._budget_acquire(
            job_id, len(all_ids) * self.pool.block_nbytes
        )
        if self._staging is not None:
            self._transfer_async_staged(job_id, groups, job_trace)
            return
        device_t0 = time.perf_counter()
        with use_trace(job_trace), obs_span("offload.stage") as stage:
            # One gather + one DMA for the whole job.
            host = self.pool.gather_to_host(all_ids)  # [L, n, 2, bs, h, d]

            paths: List[str] = []
            buffers: List[np.ndarray] = []
            cursor = 0
            for file_hash, ids in groups:
                paths.append(self.file_mapper.get_file_name(file_hash))
                chunk = host[:, cursor : cursor + len(ids)]
                # Layer-major gather -> block-major file bytes (see
                # module docstring: head-of-file == first blocks).
                buffers.append(
                    np.ascontiguousarray(np.moveaxis(chunk, 1, 0))
                )
                cursor += len(ids)
            device_s = time.perf_counter() - device_t0
            if self._host_cache is not None:
                admitted = [
                    file_hash
                    for (file_hash, _), buffer in zip(groups, buffers)
                    if self._host_cache.put(file_hash, buffer)
                ]
                # Advertise only what the budget actually admitted.
                if admitted and self._event_sink is not None:
                    self._event_sink(admitted, HOST_MEDIUM)
            stage.set_attr("files", len(paths))
        self._job_hashes[job_id] = (
            [h for h, _ in groups],
            sum(buffer.nbytes for buffer in buffers),
            device_s,
        )
        self._trace_io_start(job_id, job_trace)
        self.engine.store(job_id, paths, buffers, skip_existing=True)

    def _transfer_async_staged(
        self, job_id: int, groups: Sequence[FileBlockGroup], job_trace
    ) -> None:
        """Staging-engine path: per-group pinned-slot pipeline; the
        host-tier admission hook copies (slots are reused)."""
        admitted: List[int] = []

        def on_group(file_hash: int, buffer: np.ndarray) -> None:
            if self._host_cache is not None and self._host_cache.put(
                file_hash, buffer.copy()
            ):
                admitted.append(file_hash)

        # Pending entry BEFORE submission so a parent that completes
        # mid-pipeline (every sub waited out) still routes here.
        self._job_hashes[job_id] = (
            [h for h, _ in groups],
            sum(len(ids) for _, ids in groups) * self.pool.block_nbytes,
            None,  # device split measured by the staging engine
        )
        self._staged.add(job_id)
        self._trace_io_start(job_id, job_trace)
        with use_trace(job_trace), obs_span("offload.stage") as stage:
            stage.set_attr("files", len(groups))
            stage.set_attr("staged", True)
            self._staging.store(job_id, groups, on_group=on_group)
        if admitted and self._event_sink is not None:
            self._event_sink(admitted, HOST_MEDIUM)

    def owns(self, job_id: int) -> bool:
        return job_id in self._job_hashes

    def on_finished(self, job_id: int, status: JobStatus) -> JobStatus:
        self._budget_release(job_id)
        self._trace_finish(job_id, status)
        io_seconds = self._io_elapsed(job_id)
        staged = self._staging_stats(job_id)
        hashes, nbytes, device_s = self._job_hashes.pop(
            job_id, (None, 0, None)
        )
        if hashes is None:
            # A completion this handler never submitted (or one already
            # harvested) points at connector routing bugs — the store
            # event for those blocks will never fire.  Never silent.
            logger.warning(
                "store completion for unknown job %d (status %s); "
                "no event will be published",
                job_id,
                status.name,
            )
        METRICS.offload_jobs.labels("store", status.name.lower()).inc()
        if status != JobStatus.SUCCEEDED:
            return status
        if staged is not None:
            # The staging engine measured the real splits: the file
            # window (first submit -> last completion) and the summed
            # gather+DMA time — tighter than submit->harvest, which
            # also counts idle-until-poll slack.
            io_seconds = staged["io_s"] or io_seconds
            device_s = staged["device_s"]
        if (
            self._rtt_observer is not None
            and io_seconds is not None
            and nbytes > 0
        ):
            # Write-side cost feed: demotion pricing needs the store
            # path measured, not mirrored from readback.
            try:
                self._rtt_observer(nbytes, io_seconds, device_s)
            except Exception:  # noqa: BLE001 — advisory feed only
                logger.exception("store rtt observer failed")
        # Counted on success only, symmetric with the load path (bytes
        # deduped by skip_existing still transit the gather+DMA).
        METRICS.offload_bytes.labels("store").inc(nbytes)
        if hashes and self._event_sink is not None:
            self._event_sink(hashes, SHARED_STORAGE_MEDIUM)
        return status


class StorageToDeviceHandler(_HandlerBase):
    """Asynchronously page blocks from shared storage into the pool.

    With a ``host_cache``, resident groups are served from host DRAM
    (memcpy, no file I/O); only the cache misses go to the engine."""

    def __init__(
        self, *args, host_cache=None, staging_budget=None,
        rtt_observer=None, staging=None,
    ):
        super().__init__(*args, staging_budget=staging_budget,
                         staging=staging)
        self._host_cache = host_cache
        # Compute-or-load feed (tiering/advisor.py): called with
        # (payload bytes, submit->harvest seconds) on every successful
        # load so the advisor's RTT model tracks the real path.
        self._rtt_observer = rtt_observer
        # job_id -> (device_block_ids, host buffers awaiting scatter,
        # bytes the engine reads from files — excludes host-tier hits)
        self._pending: Dict[
            int, Tuple[List[int], List[np.ndarray], int]
        ] = {}

    def transfer_async(
        self, job_id: int, groups: Sequence[FileBlockGroup]
    ) -> None:
        c = self.pool.config
        n_blocks = sum(len(ids) for _, ids in groups)
        job_trace = self._trace_submit("offload.load", job_id, n_blocks)
        self._budget_acquire(job_id, n_blocks * self.pool.block_nbytes)
        if self._staging is not None:
            self._transfer_async_staged(job_id, groups, job_trace)
            return
        with use_trace(job_trace), obs_span("offload.stage") as stage:
            paths: List[str] = []
            buffers: List[np.ndarray] = []
            file_buffers: List[np.ndarray] = []
            all_ids: List[int] = []
            for file_hash, ids in groups:
                cached = (
                    self._host_cache.get(file_hash)
                    if self._host_cache is not None
                    else None
                )
                if cached is not None and cached.shape[0] >= len(ids):
                    # Host-tier hit: a partial request reads the group's
                    # head blocks (block-major layout invariant).
                    buffers.append(cached[: len(ids)])
                else:
                    buffer = np.empty(
                        (
                            len(ids),
                            c.num_layers,
                            2,
                            c.block_size,
                            c.num_kv_heads,
                            c.head_dim,
                        ),
                        dtype=host_dtype(c.dtype),
                    )
                    buffers.append(buffer)
                    file_buffers.append(buffer)
                    paths.append(self.file_mapper.get_file_name(file_hash))
                all_ids.extend(ids)
            stage.set_attr("files", len(paths))
            stage.set_attr("host_tier_hits", len(buffers) - len(file_buffers))
        # file_nbytes = what the engine actually reads from storage;
        # the RTT observer must see ONLY these bytes (a host-tier-hit-
        # heavy job pairs a near-zero io time with its full payload,
        # which would collapse the advisor's per-byte cost estimate).
        file_nbytes = sum(buffer.nbytes for buffer in file_buffers)
        self._pending[job_id] = (all_ids, buffers, file_nbytes)
        self._trace_io_start(job_id, job_trace)
        # Zero-file jobs still register so get_finished reports them.
        self.engine.load(job_id, paths, file_buffers)

    def _transfer_async_staged(
        self, job_id: int, groups: Sequence[FileBlockGroup], job_trace
    ) -> None:
        """Staging-engine path: host-tier hits scatter immediately,
        file-backed groups pipeline through the lane slots (the
        staging engine scatters each as its read lands)."""
        file_groups: List[FileBlockGroup] = []
        host_hits = 0
        with use_trace(job_trace), obs_span("offload.stage") as stage:
            for file_hash, ids in groups:
                cached = (
                    self._host_cache.get(file_hash)
                    if self._host_cache is not None
                    else None
                )
                if cached is not None and cached.shape[0] >= len(ids):
                    # Host-tier hit: head blocks of the cached group
                    # (block-major layout invariant), device-bound now
                    # — serialized with the staging engine's
                    # harvest-time scatters.
                    self._staging.scatter_block_major(
                        list(ids), cached[: len(ids)]
                    )
                    host_hits += 1
                else:
                    file_groups.append((file_hash, ids))
            stage.set_attr("files", len(file_groups))
            stage.set_attr("host_tier_hits", host_hits)
            stage.set_attr("staged", True)
        file_nbytes = (
            sum(len(ids) for _, ids in file_groups)
            * self.pool.block_nbytes
        )
        # Buffers live in the staging engine's slots; the pending entry
        # carries an empty buffer list so on_finished skips the
        # one-shot concatenate+scatter (already done per group).
        self._pending[job_id] = (
            [i for _, ids in groups for i in ids],
            [],
            file_nbytes,
        )
        self._staged.add(job_id)
        self._trace_io_start(job_id, job_trace)
        self._staging.load(job_id, file_groups)

    def owns(self, job_id: int) -> bool:
        return job_id in self._pending

    def on_finished(self, job_id: int, status: JobStatus) -> JobStatus:
        self._budget_release(job_id)
        self._trace_finish(job_id, status)
        io_seconds = self._io_elapsed(job_id)
        staged = self._staging_stats(job_id)
        pending = self._pending.pop(job_id, None)
        METRICS.offload_jobs.labels("load", status.name.lower()).inc()
        if pending is None:
            # An unknown load completion means the scatter for those
            # blocks never runs — the pool is silently missing data the
            # caller believes was paged in.  Never silent.
            logger.warning(
                "load completion for unknown job %d (status %s); "
                "scatter skipped",
                job_id,
                status.name,
            )
            return status
        if status != JobStatus.SUCCEEDED:
            return status
        block_ids, buffers, file_nbytes = pending
        METRICS.offload_bytes.labels("load").inc(
            len(block_ids) * self.pool.block_nbytes
            if staged is not None
            else sum(buffer.nbytes for buffer in buffers)
        )
        if staged is not None:
            # The staging engine measured the file window directly —
            # tighter than submit->harvest (no idle-until-poll slack).
            io_seconds = staged["io_s"] or io_seconds
        if (
            self._rtt_observer is not None
            and io_seconds is not None
            and file_nbytes > 0
        ):
            # Only real file I/O informs the readback cost model: a
            # host-tier-served job's near-zero io time says nothing
            # about storage bandwidth.
            try:
                self._rtt_observer(file_nbytes, io_seconds)
            except Exception:  # noqa: BLE001 — advisory feed only
                logger.exception("rtt observer failed")
        if staged is None:
            host = np.concatenate(
                [np.moveaxis(b, 0, 1) for b in buffers], axis=1
            )
            self.pool.scatter_from_host(block_ids, host)
        # Staged groups were scattered as each file read landed.
        return status
