"""Worker-side offload handlers: TPU HBM <-> shared storage.

The store path is *one device gather + one DMA + async file fanout*: the
handler gathers every requested block (all layers at once) into a single
contiguous host array, slices per-file views, and hands them to the native
I/O engine — replacing the reference's per-block-per-layer
``cudaMemcpyAsync`` loop + CUDA-event fencing (storage_offload.cpp:145-239,
tensor_copier.cu:50-97) with XLA's DMA engine.

The load path is the mirror: async file reads into host buffers, then on
completion one upload + jitted scatter into the cache pool.  Because the
scatter must wait for the file bytes, loads finish at harvest time
(``get_finished``/``wait``), keeping the serving step free of blocking I/O.

File grouping: an offloaded block = ``blocks_per_file`` device blocks; the
*first* file of a transfer may carry fewer (a partial group), mirroring the
reference's grouping (worker.py:100-117).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import ml_dtypes  # ships with jax; registers bfloat16 as a numpy dtype
import numpy as np


def host_dtype(name: str) -> np.dtype:
    """Numpy dtype for host staging buffers, incl. bf16 via ml_dtypes."""
    if name == "bfloat16":
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)

from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import KVCachePool
from llm_d_kv_cache_manager_tpu.native.engine import (
    JobStatus,
    OffloadEngine,
)
from llm_d_kv_cache_manager_tpu.offload.file_mapper import FileMapper
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("offload.worker")

# (file_hash, device_block_ids) — one file per offloaded block group.
FileBlockGroup = Tuple[int, Sequence[int]]

# Called with (file_hashes, medium) when a store job lands, so the pod can
# advertise the new tier in its KVEvents stream.
StoreEventSink = Callable[[List[int], str], None]

SHARED_STORAGE_MEDIUM = "shared_storage"


def group_blocks_per_file(
    file_hashes: Sequence[int],
    block_ids: Sequence[int],
    blocks_per_file: int,
) -> List[FileBlockGroup]:
    """Group device block ids under their file hashes.

    The first group may be partial (when the transfer starts mid-group);
    all later groups are full.
    """
    if not file_hashes:
        return []
    remainder = len(block_ids) - (len(file_hashes) - 1) * blocks_per_file
    if remainder <= 0 or remainder > blocks_per_file:
        raise ValueError(
            f"{len(block_ids)} blocks cannot split into {len(file_hashes)} "
            f"files of up to {blocks_per_file}"
        )
    groups: List[FileBlockGroup] = []
    cursor = 0
    for i, file_hash in enumerate(file_hashes):
        take = remainder if i == 0 else blocks_per_file
        groups.append((file_hash, list(block_ids[cursor : cursor + take])))
        cursor += take
    return groups


class _HandlerBase:
    """Shared-engine handler.

    Both handlers submit jobs to one engine, so raw ``engine.get_finished``
    interleaves their completions; each handler claims only its own job ids
    via ``owns``/``on_finished``, and the connector routes the harvest.
    Job ids must be unique across the connector.
    """

    def __init__(
        self,
        pool: KVCachePool,
        engine: OffloadEngine,
        file_mapper: FileMapper,
    ) -> None:
        self.pool = pool
        self.engine = engine
        self.file_mapper = file_mapper

    def owns(self, job_id: int) -> bool:
        raise NotImplementedError

    def on_finished(self, job_id: int, status: JobStatus) -> JobStatus:
        """Completion hook; returns the (possibly updated) status."""
        raise NotImplementedError

    def wait(self, job_id: int) -> JobStatus:
        return self.on_finished(job_id, self.engine.wait(job_id))


class DeviceToStorageHandler(_HandlerBase):
    """Asynchronously persist device blocks to shared storage."""

    def __init__(self, *args, event_sink: Optional[StoreEventSink] = None):
        super().__init__(*args)
        self._event_sink = event_sink
        self._job_hashes: Dict[int, List[int]] = {}

    def transfer_async(
        self, job_id: int, groups: Sequence[FileBlockGroup]
    ) -> None:
        all_ids: List[int] = []
        for _, ids in groups:
            all_ids.extend(ids)
        # One gather + one DMA for the whole job.
        host = self.pool.gather_to_host(all_ids)  # [L, n, 2, bs, h, d]

        paths: List[str] = []
        buffers: List[np.ndarray] = []
        cursor = 0
        for file_hash, ids in groups:
            paths.append(self.file_mapper.get_file_name(file_hash))
            chunk = host[:, cursor : cursor + len(ids)]
            buffers.append(np.ascontiguousarray(chunk))
            cursor += len(ids)
        self._job_hashes[job_id] = [h for h, _ in groups]
        self.engine.store(job_id, paths, buffers, skip_existing=True)

    def owns(self, job_id: int) -> bool:
        return job_id in self._job_hashes

    def on_finished(self, job_id: int, status: JobStatus) -> JobStatus:
        hashes = self._job_hashes.pop(job_id, None)
        if (
            status == JobStatus.SUCCEEDED
            and hashes
            and self._event_sink is not None
        ):
            self._event_sink(hashes, SHARED_STORAGE_MEDIUM)
        return status


class StorageToDeviceHandler(_HandlerBase):
    """Asynchronously page blocks from shared storage into the pool."""

    def __init__(self, *args):
        super().__init__(*args)
        # job_id -> (device_block_ids, host buffers awaiting scatter)
        self._pending: Dict[int, Tuple[List[int], List[np.ndarray]]] = {}

    def transfer_async(
        self, job_id: int, groups: Sequence[FileBlockGroup]
    ) -> None:
        c = self.pool.config
        paths: List[str] = []
        buffers: List[np.ndarray] = []
        all_ids: List[int] = []
        for file_hash, ids in groups:
            paths.append(self.file_mapper.get_file_name(file_hash))
            buffers.append(
                np.empty(
                    (
                        c.num_layers,
                        len(ids),
                        2,
                        c.block_size,
                        c.num_kv_heads,
                        c.head_dim,
                    ),
                    dtype=host_dtype(c.dtype),
                )
            )
            all_ids.extend(ids)
        self._pending[job_id] = (all_ids, buffers)
        self.engine.load(job_id, paths, buffers)

    def owns(self, job_id: int) -> bool:
        return job_id in self._pending

    def on_finished(self, job_id: int, status: JobStatus) -> JobStatus:
        pending = self._pending.pop(job_id, None)
        if pending is None or status != JobStatus.SUCCEEDED:
            return status
        block_ids, buffers = pending
        host = np.concatenate(buffers, axis=1)
        self.pool.scatter_from_host(block_ids, host)
        return status
