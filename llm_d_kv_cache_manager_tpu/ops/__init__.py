"""TPU compute ops: attention variants used by the serving stack.

- ``attention``: dense causal GQA (prefill / training path).
- ``ring_attention``: sequence-parallel blockwise attention over an
  ``sp`` mesh axis (ppermute ring over ICI) for long-context prefill;
  ``striped=True`` + ``stripe``/``unstripe`` select the interleaved
  layout whose causal masks balance across ring steps, and
  ``impl="flash"`` runs each step through the mask-aware Pallas
  partial (ring_flash_pallas.py) that skips masked sub-tiles — with
  striping, ~half the per-step MXU work.
- ``paged_attention``: decode-time attention over the paged KV pool
  (block-table gather), the TPU analogue of vLLM's paged attention.
"""

from llm_d_kv_cache_manager_tpu.ops.attention import causal_gqa_attention
from llm_d_kv_cache_manager_tpu.ops.paged_attention import paged_attention
from llm_d_kv_cache_manager_tpu.ops.ring_attention import (
    ring_attention,
    ring_attention_sharded,
    stripe,
    unstripe,
)

__all__ = [
    "causal_gqa_attention",
    "ring_attention",
    "ring_attention_sharded",
    "stripe",
    "unstripe",
    "paged_attention",
]
