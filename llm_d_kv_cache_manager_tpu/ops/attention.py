"""Dense causal grouped-query attention.

The dense path used for training steps and short-prompt prefill.  Kept
as one einsum-shaped function so XLA maps the contractions onto the MXU
and fuses the softmax; no hand scheduling.  Accumulation is float32
regardless of input dtype (bf16 in, f32 softmax, bf16 out).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -1e30


def causal_gqa_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_offset: int | jnp.ndarray = 0,
    kv_len: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Causal attention with grouped KV heads.

    q: [B, Tq, H, D]; k/v: [B, Tk, Hkv, D] with H % Hkv == 0.
    ``q_offset`` is the absolute position of q[0] within the key axis
    (decode: Tq=1, q_offset=context_len-1).  ``kv_len`` ([B]) masks
    padded keys beyond each sequence's real length.
    Returns [B, Tq, H, D] in q.dtype.
    """
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    groups = H // Hkv

    qf = q.astype(jnp.float32) * (D**-0.5)
    qf = qf.reshape(B, Tq, Hkv, groups, D)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k.astype(jnp.float32))

    q_pos = jnp.arange(Tq)[:, None] + q_offset
    k_pos = jnp.arange(Tk)[None, :]
    mask = k_pos <= q_pos  # [Tq, Tk]
    if kv_len is not None:
        mask = mask[None] & (k_pos[None] < kv_len[:, None, None])  # [B,Tq,Tk]
        mask = mask[:, None, None]  # [B,1,1,Tq,Tk]
    else:
        mask = mask[None, None, None]
    scores = jnp.where(mask, scores, NEG_INF)

    weights = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    weights = weights / weights.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, v.astype(jnp.float32))
    return out.reshape(B, Tq, H, D).astype(q.dtype)
