"""Blockwise causal GQA attention (flash-style online softmax).

Dense attention materializes the [Tq, Tk] score matrix — 4.5 GB of f32
for one 8k-token head group — which caps prefill length well below the
long-context scale this framework treats as first-class.  This op tiles
the computation: an outer ``lax.scan`` over query blocks, an inner scan
over key/value chunks carrying the online-softmax state (running max,
denominator, weighted accumulator), so peak memory is one
[q_block, kv_block] tile per head group regardless of sequence length.

TPU mapping: every tile op is an einsum on the MXU; the scans are
compiler-friendly static-trip-count loops; fully-masked chunks (the
upper causal triangle) are skipped at *runtime* with ``lax.cond`` so
causal prefill does ~half the FLOPs, like a hand-written flash kernel.
f32 accumulation throughout, bf16 in/out.

Same contract as ops/attention.py::causal_gqa_attention (q_offset for
continuation/decode, kv_len for padded keys); equivalence is pinned by
tests/test_flash_attention.py.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def flash_gqa_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_offset: int | jnp.ndarray = 0,
    kv_len: Optional[jnp.ndarray] = None,
    q_block: int = 256,
    kv_block: int = 256,
) -> jnp.ndarray:
    """Causal GQA attention, tiled.  q: [B, Tq, H, D]; k/v:
    [B, Tk, Hkv, D]; returns [B, Tq, H, D] in q.dtype."""
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    groups = H // Hkv

    q_block = min(q_block, Tq)
    kv_block = min(kv_block, Tk)
    q_pad = (-Tq) % q_block
    k_pad = (-Tk) % kv_block
    if k_pad:
        # Padded keys are masked off by position (k_pos >= Tk).
        k = jnp.pad(k, ((0, 0), (0, k_pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, k_pad), (0, 0), (0, 0)))
    if q_pad:
        q = jnp.pad(q, ((0, 0), (0, q_pad), (0, 0), (0, 0)))
    nq = (Tq + q_pad) // q_block
    nk = (Tk + k_pad) // kv_block

    # Scan inputs stay in the storage dtype (bf16 KV is not copied to
    # f32 up front — that would dominate peak memory at long context);
    # tiles are cast to f32 inside the attend body.
    # [nq, B, q_block, Hkv, G, D] / [nk, B, kv_block, Hkv, D]
    qs = q.reshape(B, nq, q_block, Hkv, groups, D).transpose(1, 0, 2, 3, 4, 5)
    ks = k.reshape(B, nk, kv_block, Hkv, D).transpose(1, 0, 2, 3, 4)
    vs = v.reshape(B, nk, kv_block, Hkv, D).transpose(1, 0, 2, 3, 4)
    k_limit = jnp.broadcast_to(
        jnp.asarray(Tk if kv_len is None else kv_len), (B,)
    )  # [B] valid key count

    def q_block_body(_, qi):
        q_tile, q_index = qi  # [B, q_block, Hkv, G, D], scalar
        q_pos = q_offset + q_index * q_block + jnp.arange(q_block)  # [q_block]

        def kv_chunk_body(carry, kc):
            m, l, o = carry
            k_tile, v_tile, k_index = kc
            k_pos = k_index * kv_block + jnp.arange(kv_block)  # [kv_block]

            def attend(args):
                m, l, o = args
                s = jnp.einsum(
                    "bqhgd,bkhd->bqhgk",
                    q_tile.astype(jnp.float32) * (D**-0.5),
                    k_tile.astype(jnp.float32),
                )  # [B, q_block, Hkv, G, kv_block]
                mask = (k_pos[None, :] <= q_pos[:, None])[None] & (
                    k_pos[None, None, :] < k_limit[:, None, None]
                )  # [B, q_block, kv_block]
                s = jnp.where(mask[:, :, None, None], s, NEG_INF)
                m_new = jnp.maximum(m, s.max(axis=-1))
                p = jnp.exp(s - m_new[..., None])
                scale = jnp.exp(m - m_new)
                l_new = l * scale + p.sum(axis=-1)
                o_new = o * scale[..., None] + jnp.einsum(
                    "bqhgk,bkhd->bqhgd", p, v_tile.astype(jnp.float32)
                )
                return m_new, l_new, o_new

            # Runtime causal skip: chunk entirely above the diagonal (or
            # entirely past every sequence's valid length) does no work.
            relevant = (k_pos[0] <= q_pos[-1]) & (k_pos[0] < k_limit.max())
            carry = lax.cond(relevant, attend, lambda args: args, (m, l, o))
            return carry, None

        m0 = jnp.full((B, q_block, Hkv, groups), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, q_block, Hkv, groups), jnp.float32)
        o0 = jnp.zeros((B, q_block, Hkv, groups, D), jnp.float32)
        (m, l, o), _ = lax.scan(
            kv_chunk_body, (m0, l0, o0), (ks, vs, jnp.arange(nk))
        )
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return None, out

    _, outs = lax.scan(q_block_body, None, (qs, jnp.arange(nq)))
    # [nq, B, q_block, Hkv, G, D] -> [B, Tq, H, D]
    out = outs.transpose(1, 0, 2, 3, 4, 5).reshape(B, Tq + q_pad, H, D)
    return out[:, :Tq].astype(q.dtype)
