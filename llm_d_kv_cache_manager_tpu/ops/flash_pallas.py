"""Pallas TPU flash-attention prefill kernel.

The scan-based op (ops/flash_attention.py) expresses flash attention as
XLA loops; this kernel owns the schedule instead: one grid program per
(batch, head, q-tile) computes its output tile with an online-softmax
``fori_loop`` over K/V chunks resident in VMEM, f32 accumulators in
VMEM scratch, every tile contraction on the MXU
(``preferred_element_type=f32``), and the causal upper triangle never
read — the loop's trip count stops at the tile's last visible chunk
(q_offset + (qi+1)*q_block), so continuation suffixes (short q over a
long cached prefix) do only the work the mask allows.

Layout: TPU block specs need the tiled axes last, so the wrapper runs
in [B, H, T, D] (transposing at the boundary; XLA fuses these into the
surrounding ops).  Grid order puts q-tiles innermost so the same
head's K/V block stays resident in VMEM across its q-tiles.

Same contract as ``flash_gqa_attention`` for static ``q_offset``;
equivalence is pinned by tests/test_flash_attention.py (interpret mode
on CPU, compiled on TPU).  The model routes long-sequence inference
here on TPU and falls back to the scan op elsewhere
(models/llama.py::_prefill_attention).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# The kernel stages one kv-head's full K and V in VMEM (~16 MB/core,
# shared with q/out tiles, f32 scratch, and pipeline double-buffering).
# Above this K+V footprint, callers should use the scan-based op, which
# streams K/V from HBM at any length.
VMEM_KV_BUDGET_BYTES = 8 * 1024 * 1024


def fits_vmem(kv_seq_len: int, head_dim: int, dtype_bytes: int = 2) -> bool:
    """True if a [kv_seq_len, head_dim] K+V pair fits the kernel's
    VMEM staging budget."""
    return 2 * kv_seq_len * head_dim * dtype_bytes <= VMEM_KV_BUDGET_BYTES


def _flash_kernel(
    q_ref,  # [1, 1, q_block, D]
    k_ref,  # [1, 1, Tk_pad, D]
    v_ref,  # [1, 1, Tk_pad, D]
    out_ref,  # [1, 1, q_block, D]
    acc_ref,  # VMEM [q_block, D] f32
    m_ref,  # VMEM [q_block, 128] f32 (lane-replicated row max)
    l_ref,  # VMEM [q_block, 128] f32 (lane-replicated row sum)
    *,
    q_offset: int,
    kv_len: int,
    q_block: int,
    kv_chunk: int,
    scale: float,
):
    qi = pl.program_id(2)
    q_start = q_offset + qi * q_block  # absolute position of q row 0

    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale  # [q_block, D]

    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)

    # Last chunk any row of this tile may see (causal): position
    # q_start + q_block - 1, clamped to the real kv length.
    last = jnp.minimum(q_start + q_block, kv_len)
    n_chunks = pl.cdiv(last, kv_chunk)

    row = jax.lax.broadcasted_iota(jnp.int32, (q_block, kv_chunk), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (q_block, kv_chunk), 1)

    def chunk_body(ci, _):
        k_start = ci * kv_chunk
        k = k_ref[0, 0, pl.ds(k_start, kv_chunk), :]  # [kv_chunk, D]
        v = v_ref[0, 0, pl.ds(k_start, kv_chunk), :]

        s = jax.lax.dot_general(
            q,
            k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [q_block, kv_chunk]

        q_pos = q_start + row
        k_pos = k_start + col
        mask = (k_pos <= q_pos) & (k_pos < kv_len)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]  # [q_block, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)  # masked entries underflow to 0
        correction = jnp.exp(m_prev - m_new)  # [q_block, 1]

        l_ref[...] = l_ref[...] * correction + jnp.sum(
            p, axis=1, keepdims=True
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[...] = acc_ref[...] * correction + jax.lax.dot_general(
            p,
            v.astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return 0

    jax.lax.fori_loop(0, n_chunks, chunk_body, 0)

    l = l_ref[:, :1]
    out = acc_ref[...] / jnp.where(l == 0.0, 1.0, l)  # pad rows: 0 not NaN
    out_ref[0, 0, :, :] = out.astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("q_offset", "q_block", "kv_chunk", "interpret"),
)
def flash_gqa_attention_pallas(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_offset: int = 0,
    q_block: int = 256,
    kv_chunk: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal GQA flash attention.  q: [B, Tq, H, D]; k/v:
    [B, Tk, Hkv, D]; ``q_offset`` shifts q positions (continuation).
    Returns [B, Tq, H, D] in q.dtype."""
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    groups = H // Hkv

    q_block = min(q_block, max(Tq, 8))
    kv_chunk = min(kv_chunk, Tk)
    q_pad = (-Tq) % q_block
    k_pad = (-Tk) % kv_chunk

    # Kernel layout: [B, H(kv), T, D] — tiled axes last.
    qt = jnp.pad(q, ((0, 0), (0, q_pad), (0, 0), (0, 0))).transpose(
        0, 2, 1, 3
    )
    kt = jnp.pad(k, ((0, 0), (0, k_pad), (0, 0), (0, 0))).transpose(
        0, 2, 1, 3
    )
    vt = jnp.pad(v, ((0, 0), (0, k_pad), (0, 0), (0, 0))).transpose(
        0, 2, 1, 3
    )
    nq = (Tq + q_pad) // q_block

    kernel = functools.partial(
        _flash_kernel,
        q_offset=q_offset,
        kv_len=Tk,
        q_block=q_block,
        kv_chunk=kv_chunk,
        scale=D**-0.5,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        grid=(B, H, nq),
        in_specs=[
            pl.BlockSpec(
                (1, 1, q_block, D),
                lambda b, h, qi: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, Tk + k_pad, D),
                lambda b, h, qi, g=groups: (b, h // g, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, Tk + k_pad, D),
                lambda b, h, qi, g=groups: (b, h // g, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, q_block, D),
            lambda b, h, qi: (b, h, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((q_block, D), jnp.float32),
            pltpu.VMEM((q_block, 128), jnp.float32),
            pltpu.VMEM((q_block, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = out.transpose(0, 2, 1, 3)
    if q_pad:
        out = out[:, :Tq]
    return out
