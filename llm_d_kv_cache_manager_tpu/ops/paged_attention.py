"""Paged decode attention over the block-table KV layout.

Decode-time attention where K/V live in the paged pool
(``models/kv_cache_pool.py`` layout: ``[num_blocks, 2, block_size,
Hkv, D]`` per layer) and each sequence names its blocks via a block
table.  The gather + attention is one jitted function: XLA emits a
dynamic-gather from HBM followed by MXU contractions, no host round
trip — the TPU analogue of vLLM's paged-attention CUDA kernel.

Static shapes: block tables are padded to ``max_blocks`` and masked by
``context_len`` so the compiled program is reused across requests.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e30


def paged_attention(
    q: jnp.ndarray,
    kv_layer: jnp.ndarray,
    block_table: jnp.ndarray,
    context_len: jnp.ndarray,
) -> jnp.ndarray:
    """q: [B, H, D]; kv_layer: [num_blocks, 2, block_size, Hkv, D];
    block_table: [B, max_blocks] int32 (pad with any valid id);
    context_len: [B] int32.  Returns [B, H, D]."""
    B, H, D = q.shape
    _, _, block_size, Hkv, _ = kv_layer.shape
    groups = H // Hkv
    max_blocks = block_table.shape[1]
    T = max_blocks * block_size

    # [B, max_blocks, 2, block_size, Hkv, D] -> [B, T, Hkv, D] x2
    gathered = jnp.take(kv_layer, block_table, axis=0)
    k = gathered[:, :, 0].reshape(B, T, Hkv, D)
    v = gathered[:, :, 1].reshape(B, T, Hkv, D)

    qf = q.astype(jnp.float32).reshape(B, Hkv, groups, D) * (D**-0.5)
    scores = jnp.einsum("bhgd,bkhd->bhgk", qf, k.astype(jnp.float32))
    mask = jnp.arange(T)[None, :] < context_len[:, None]  # [B, T]
    scores = jnp.where(mask[:, None, None], scores, NEG_INF)

    weights = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    weights = weights / weights.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhgk,bkhd->bhgd", weights, v.astype(jnp.float32))
    return out.reshape(B, H, D).astype(q.dtype)
