"""Pallas TPU paged-decode attention kernel.

The XLA version (ops/paged_attention.py) gathers every table block into
a dense [B, T, Hkv, D] tensor before attending — the whole context's
KV crosses HBM twice (pool -> gathered copy -> compute reads).  This
kernel is the TPU analogue of vLLM's paged-attention CUDA kernel: the
block table rides in as a scalar-prefetch operand, each grid step's
``index_map`` points straight at that sequence's next pool block, and
Pallas's pipeline DMAs exactly the referenced blocks HBM->VMEM
(double-buffered) while the MXU works on the previous one.  Past the
context length the index map pins to the last valid block — an
unchanged index skips the redundant DMA — and the flash accumulators
(f32, VMEM scratch) carry the online softmax across grid steps.

Contract matches ops/paged_attention.py::paged_attention; equivalence
is pinned by tests/test_paged_decode_pallas.py (interpret mode on CPU,
compiled on TPU via bench paths).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


# Default pool blocks fetched per grid step: amortizes per-step
# pipeline overhead (528 one-block steps left the MXU mostly idle)
# while each block still arrives through its own independently-
# pipelined DMA.  bench.py's detail.kernels sweeps this on the real
# chip and routes the winner via LlamaConfig.decode_blocks_per_step.
BLOCKS_PER_STEP = 4


def _decode_kernel(
    table_ref,  # SMEM [B, max_blocks] int32 (scalar prefetch)
    ctx_ref,  # SMEM [B] int32 (scalar prefetch)
    q_ref,  # VMEM [1, H, D]
    *rest,  # blocks_per_step kv refs, out ref, then scratch
    block_size: int,
    groups: int,
    scale: float,
    blocks_per_step: int,
    mxu_native: bool,
):
    kv_refs = rest[:blocks_per_step]
    out_ref = rest[blocks_per_step]
    m_ref, l_ref, acc_ref = rest[blocks_per_step + 1 :]

    b = pl.program_id(0)
    j = pl.program_id(1)
    n_steps = pl.num_programs(1)
    ctx = ctx_ref[b]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    H = q_ref.shape[1]
    D = q_ref.shape[2]
    Hkv = kv_refs[0].shape[3]
    # mxu_native: feed the dots bf16 operands with f32 accumulation (the
    # MXU's native mode) instead of upcasting K/V after the DMA — saves
    # the VPU cast and halves the operands' VMEM footprint.  Softmax
    # statistics and accumulators stay f32 either way.
    compute_dtype = q_ref.dtype if mxu_native else jnp.float32
    q = q_ref[0].astype(jnp.float32) * scale  # [H, D]
    qb = q.reshape(Hkv, groups, D).astype(compute_dtype)

    for i, kv_ref in enumerate(kv_refs):
        # Valid positions in sub-block i: [(j*P+i)*bs, ctx).
        valid = ctx - (j * blocks_per_step + i) * block_size

        @pl.when(valid > 0)
        def _attend(kv_ref=kv_ref, valid=valid):
            k = kv_ref[0, 0].astype(compute_dtype)  # [bs, Hkv, D]
            v = kv_ref[0, 1].astype(compute_dtype)
            kb = k.transpose(1, 0, 2)  # [Hkv, bs, D]
            vb = v.transpose(1, 0, 2)
            s = jax.lax.dot_general(
                qb,
                kb,
                (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [Hkv, G, bs]
            s = s.reshape(H, block_size)
            col = jax.lax.broadcasted_iota(
                jnp.int32, (H, block_size), 1
            )
            s = jnp.where(col < valid, s, NEG_INF)

            m_prev = m_ref[:, :1]
            m_new = jnp.maximum(
                m_prev, jnp.max(s, axis=1, keepdims=True)
            )
            p = jnp.exp(s - m_new)  # [H, bs] f32
            correction = jnp.exp(m_prev - m_new)
            l_ref[...] = l_ref[...] * correction + jnp.sum(
                p, axis=1, keepdims=True
            )
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            pb = p.reshape(Hkv, groups, block_size).astype(compute_dtype)
            o = jax.lax.dot_general(
                pb,
                vb,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [Hkv, G, D]
            acc_ref[...] = acc_ref[...] * correction + o.reshape(H, D)

    @pl.when(j == n_steps - 1)
    def _finalize():
        l = l_ref[:, :1]
        out = acc_ref[...] / jnp.where(l == 0.0, 1.0, l)
        out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("interpret", "blocks_per_step", "mxu_native"),
)
def paged_decode_attention_pallas(
    q: jnp.ndarray,
    kv_layer: jnp.ndarray,
    block_table: jnp.ndarray,
    context_len: jnp.ndarray,
    *,
    interpret: bool = False,
    blocks_per_step: int = BLOCKS_PER_STEP,
    mxu_native: bool = False,
) -> jnp.ndarray:
    """q: [B, H, D]; kv_layer: [num_blocks, 2, bs, Hkv, D];
    block_table: [B, max_blocks] int32; context_len: [B] int32.
    Returns [B, H, D] in q.dtype.

    ``mxu_native=True`` keeps the attention dots in the input dtype
    (bf16 operands, f32 accumulation) instead of upcasting K/V to f32 in
    VMEM; bench.py's kernel sweep measures both and routes the winner.
    """
    B, H, D = q.shape
    _, _, block_size, Hkv, _ = kv_layer.shape
    groups = H // Hkv
    max_blocks = block_table.shape[1]
    P_STEP = blocks_per_step
    n_steps = -(-max_blocks // P_STEP)
    if max_blocks % P_STEP:
        # Pad table columns; pads resolve to the last valid block and
        # are masked by context_len in the kernel.
        block_table = jnp.pad(
            block_table,
            ((0, 0), (0, n_steps * P_STEP - max_blocks)),
        )

    def kv_index(i):
        # Sub-block i of step j; past-context steps revisit the last
        # valid block (an unchanged index skips the DMA).
        def index(b, j, table_ref, ctx_ref):
            jc = jnp.minimum(
                j * P_STEP + i,
                jnp.maximum((ctx_ref[b] - 1) // block_size, 0),
            )
            return (table_ref[b, jc], 0, 0, 0, 0)

        return index

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_steps),
        in_specs=[
            pl.BlockSpec(
                (1, H, D),
                lambda b, j, *_: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ]
        + [
            pl.BlockSpec(
                (1, 2, block_size, Hkv, D),
                kv_index(i),
                memory_space=pltpu.VMEM,
            )
            for i in range(P_STEP)
        ],
        out_specs=pl.BlockSpec(
            (1, H, D),
            lambda b, j, *_: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        block_size=block_size,
        groups=groups,
        scale=D**-0.5,
        blocks_per_step=P_STEP,
        mxu_native=mxu_native,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        block_table.astype(jnp.int32),
        context_len.astype(jnp.int32),
        q,
        *([kv_layer] * P_STEP),
    )
