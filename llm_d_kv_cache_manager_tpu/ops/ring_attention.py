"""Ring attention: sequence-parallel causal attention over an ``sp``
mesh axis.

Long-context prefill shards the sequence across devices; each device
holds a contiguous chunk of Q/K/V.  K/V chunks rotate around the ring
via ``lax.ppermute`` (one ICI hop per step) while each device keeps an
online-softmax accumulator for its local queries — flash-attention
semantics distributed over the mesh, compute overlapping the permute.

This is the TPU-native answer to the reference's "long prompts stream
through chunked hashing" scope note (SURVEY §2.3): here long prompts
also *compute* in chunks, across chips.  Use under ``shard_map`` with
q/k/v sharded on the sequence axis, or via ``ring_attention`` which
wraps the shard_map given a mesh.

Known performance note: contiguous chunking under causal masking is
load-imbalanced — device 0's queries are fully masked after one step
while the last device's stay visible every step.  ``striped=True``
selects the rebalanced layout (tokens interleave across devices via
``stripe``/``unstripe``; the causal mask becomes a near-uniform band
per step).  Scope honestly: the CURRENT body computes the full
Tq x Tk einsum and masks with where() in both layouts, so neither
realizes FLOP savings yet — the striped layout is the foundation (its
masks and exactness are pinned by tests) for a mask-aware inner
kernel (Pallas sub-block skipping) where the balance converts into
wall-clock.  The model's ``forward(sp_mesh=...)`` keeps the
contiguous ring (simpler block tables, exactness-tested).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    striped: bool = False,
) -> jnp.ndarray:
    """Per-device body. q/k/v: [B, T_local, H(kv), D]; causal over the
    global sequence.

    ``striped=False``: chunk i holds contiguous positions
    [i*T_local, (i+1)*T_local).  ``striped=True``: chunk i holds the
    interleaved stripe {t : t % R == i} in ascending order (see
    ``stripe``), so local row a on chunk c is global position a*R + c —
    the causal mask becomes the near-uniform band ``b <= a - (src >
    my_idx)`` and every device does almost equal work at every ring
    step (the contiguous layout leaves early chunks idle once their
    queries are past all rotated keys)."""
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    groups = H // Hkv
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)

    qf = q.astype(jnp.float32).reshape(B, Tq, Hkv, groups, D) * (D**-0.5)

    # Derive accumulators from qf so they carry shard_map's
    # varying-manual-axes type (a fresh jnp.zeros would not).
    o = jnp.zeros_like(qf)
    zero = jnp.zeros_like(qf[..., 0]).transpose(0, 2, 3, 1)  # [B,Hkv,g,Tq]
    m = zero + NEG_INF
    l = zero

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def accumulate(i, o, m, l, k_cur, v_cur):
        src = (my_idx - i) % axis_size  # ring position k_cur came from

        scores = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qf, k_cur.astype(jnp.float32)
        )
        if striped:
            # Global positions: query a*R + my_idx vs key b*R + src;
            # b*R + src <= a*R + my_idx  <=>  b <= a - (src > my_idx).
            mask = jnp.arange(Tk)[None, :] <= (
                jnp.arange(Tq)[:, None]
                - (src > my_idx).astype(jnp.int32)
            )
        else:
            q_pos = my_idx * Tq + jnp.arange(Tq)[:, None]
            k_pos = src * Tk + jnp.arange(Tk)[None, :]
            mask = k_pos <= q_pos  # [Tq, Tk] causal, global positions
        scores = jnp.where(mask[None, None, None], scores, NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        # Keep exp() away from the -inf sentinel when a chunk is fully
        # masked (fresh accumulator, future chunk): scale becomes exp(0).
        m_safe = jnp.maximum(m_new, 0.5 * NEG_INF)
        scale = jnp.exp(jnp.maximum(m, 0.5 * NEG_INF) - m_safe)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(mask[None, None, None], p, 0.0)

        l = l * scale + p.sum(axis=-1)
        o = o * scale.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
            "bhgqk,bkhd->bqhgd", p, v_cur.astype(jnp.float32)
        )
        return o, m_new, l

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        o, m, l = accumulate(i, o, m, l, k_cur, v_cur)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_next, v_next

    # Last chunk accumulates outside the loop: no wasted final ppermute.
    o, m, l, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, step, (o, m, l, k, v)
    )
    o, m, l = accumulate(axis_size - 1, o, m, l, k_last, v_last)
    l = jnp.maximum(l, 1e-20)
    o = o / l.transpose(0, 3, 1, 2)[..., None]
    return o.reshape(B, Tq, H, D).astype(q.dtype)


def stripe(x: jnp.ndarray, ring_size: int, axis: int = 1) -> jnp.ndarray:
    """Permute a sequence axis into the striped ring layout: global
    token t goes to chunk t % ring_size, slot t // ring_size — so that
    sharding the result contiguously over the ring gives each device an
    interleaved stripe.  Static permutation (trace-time indices)."""
    T = x.shape[axis]
    if T % ring_size:
        raise ValueError(f"sequence {T} not divisible by ring {ring_size}")
    idx = np.arange(T).reshape(T // ring_size, ring_size).T.reshape(-1)
    return jnp.take(x, jnp.asarray(idx), axis=axis)


def unstripe(x: jnp.ndarray, ring_size: int, axis: int = 1) -> jnp.ndarray:
    """Inverse of :func:`stripe` — which is itself a stripe with the
    complementary factor (the permutation t -> (t % R)*(T/R) + t//R is
    inverted by the same map with R' = T/R)."""
    T = x.shape[axis]
    if T % ring_size:
        raise ValueError(f"sequence {T} not divisible by ring {ring_size}")
    return stripe(x, T // ring_size, axis=axis)


def ring_attention_sharded(
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = None,
    striped: bool = False,
):
    """The in-jit form: returns a callable ``(q, k, v) -> out`` over
    already-sharded [B, T, H(kv), D] arrays (T over ``axis_name``, B
    over ``batch_axis``).  Model code calls this inside its own jit —
    shard_map composes under jit; no device_put happens here.

    ``head_axis`` (e.g. ``"tp"``) shards the head dimension too — the
    tp×sp composition: each shard runs the ring over its own head
    slice (attention is head-independent; GQA group count is preserved
    since H and Hkv divide by the same degree).  Left None, heads are
    replicated over the mesh and tp-sharded inputs would be
    all-gathered per call.

    ``striped=True`` expects q/k/v already in the :func:`stripe` layout
    (and returns output in it — :func:`unstripe` after): the causal
    work balances across ring steps instead of concentrating on the
    last chunks.  RoPE/position embeddings must be applied BEFORE
    striping (or with striped position vectors) — positions are
    physical token indices, not stripe slots."""
    bspec = batch_axis if batch_axis else None
    spec = P(bspec, axis_name, head_axis, None)
    local = functools.partial(
        _ring_attention_local, axis_name=axis_name, striped=striped
    )
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
    striped: bool = False,
) -> jnp.ndarray:
    """Eager convenience: place q/k/v ([B, T, H, D]; T sharded over
    ``axis_name``, B over ``batch_axis``) and run the ring.

    With ``striped=True`` the inputs/output are in PHYSICAL token order
    — this wrapper stripes them in, runs the balanced ring, and
    unstripes the output."""
    ring_size = mesh.shape[axis_name]
    if striped:
        q = stripe(q, ring_size)
        k = stripe(k, ring_size)
        v = stripe(v, ring_size)
    bspec = batch_axis if batch_axis else None
    spec = P(bspec, axis_name, None, None)
    fn = ring_attention_sharded(
        mesh, axis_name, batch_axis, striped=striped
    )
    q = jax.device_put(q, NamedSharding(mesh, spec))
    k = jax.device_put(k, NamedSharding(mesh, spec))
    v = jax.device_put(v, NamedSharding(mesh, spec))
    out = fn(q, k, v)
    if striped:
        out = unstripe(out, ring_size)
    return out
