"""Ring attention: sequence-parallel causal attention over an ``sp``
mesh axis.

Long-context prefill shards the sequence across devices; each device
holds a contiguous chunk of Q/K/V.  K/V chunks rotate around the ring
via ``lax.ppermute`` (one ICI hop per step) while each device keeps an
online-softmax accumulator for its local queries — flash-attention
semantics distributed over the mesh, compute overlapping the permute.

This is the TPU-native answer to the reference's "long prompts stream
through chunked hashing" scope note (SURVEY §2.3): here long prompts
also *compute* in chunks, across chips.  Use under ``shard_map`` with
q/k/v sharded on the sequence axis, or via ``ring_attention`` which
wraps the shard_map given a mesh.

Known performance note: contiguous chunking under causal masking is
load-imbalanced — device 0's queries finish attending after one step
while the last device works every step (utilization ~(R+1)/2R of peak
for ring size R).  Striped/zigzag layouts rebalance this by
interleaving token stripes per device at the cost of a global
permutation and stripe-aware masks; at the dryrun scale and current
prefill shapes the simple contiguous ring is preferred for its
exactness against the dense reference and simpler block tables.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
) -> jnp.ndarray:
    """Per-device body. q/k/v: [B, T_local, H(kv), D]; causal over the
    global sequence; chunk i of the ring holds positions
    [i*T_local, (i+1)*T_local)."""
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    groups = H // Hkv
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)

    qf = q.astype(jnp.float32).reshape(B, Tq, Hkv, groups, D) * (D**-0.5)

    # Derive accumulators from qf so they carry shard_map's
    # varying-manual-axes type (a fresh jnp.zeros would not).
    o = jnp.zeros_like(qf)
    zero = jnp.zeros_like(qf[..., 0]).transpose(0, 2, 3, 1)  # [B,Hkv,g,Tq]
    m = zero + NEG_INF
    l = zero

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def accumulate(i, o, m, l, k_cur, v_cur):
        src = (my_idx - i) % axis_size  # ring position k_cur came from

        scores = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qf, k_cur.astype(jnp.float32)
        )
        q_pos = my_idx * Tq + jnp.arange(Tq)[:, None]
        k_pos = src * Tk + jnp.arange(Tk)[None, :]
        mask = k_pos <= q_pos  # [Tq, Tk] causal over global positions
        scores = jnp.where(mask[None, None, None], scores, NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        # Keep exp() away from the -inf sentinel when a chunk is fully
        # masked (fresh accumulator, future chunk): scale becomes exp(0).
        m_safe = jnp.maximum(m_new, 0.5 * NEG_INF)
        scale = jnp.exp(jnp.maximum(m, 0.5 * NEG_INF) - m_safe)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(mask[None, None, None], p, 0.0)

        l = l * scale + p.sum(axis=-1)
        o = o * scale.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
            "bhgqk,bkhd->bqhgd", p, v_cur.astype(jnp.float32)
        )
        return o, m_new, l

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        o, m, l = accumulate(i, o, m, l, k_cur, v_cur)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_next, v_next

    # Last chunk accumulates outside the loop: no wasted final ppermute.
    o, m, l, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, step, (o, m, l, k, v)
    )
    o, m, l = accumulate(axis_size - 1, o, m, l, k_last, v_last)
    l = jnp.maximum(l, 1e-20)
    o = o / l.transpose(0, 3, 1, 2)[..., None]
    return o.reshape(B, Tq, H, D).astype(q.dtype)


def ring_attention_sharded(
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = None,
):
    """The in-jit form: returns a callable ``(q, k, v) -> out`` over
    already-sharded [B, T, H(kv), D] arrays (T over ``axis_name``, B
    over ``batch_axis``).  Model code calls this inside its own jit —
    shard_map composes under jit; no device_put happens here.

    ``head_axis`` (e.g. ``"tp"``) shards the head dimension too — the
    tp×sp composition: each shard runs the ring over its own head
    slice (attention is head-independent; GQA group count is preserved
    since H and Hkv divide by the same degree).  Left None, heads are
    replicated over the mesh and tp-sharded inputs would be
    all-gathered per call."""
    bspec = batch_axis if batch_axis else None
    spec = P(bspec, axis_name, head_axis, None)
    local = functools.partial(_ring_attention_local, axis_name=axis_name)
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
) -> jnp.ndarray:
    """Eager convenience: place q/k/v ([B, T, H, D]; T sharded over
    ``axis_name``, B over ``batch_axis``) and run the ring."""
    bspec = batch_axis if batch_axis else None
    spec = P(bspec, axis_name, None, None)
    fn = ring_attention_sharded(mesh, axis_name, batch_axis)
    q = jax.device_put(q, NamedSharding(mesh, spec))
    k = jax.device_put(k, NamedSharding(mesh, spec))
    v = jax.device_put(v, NamedSharding(mesh, spec))
    return fn(q, k, v)
