"""Ring attention: sequence-parallel causal attention over an ``sp``
mesh axis.

Long-context prefill shards the sequence across devices; each device
holds a contiguous chunk of Q/K/V.  K/V chunks rotate around the ring
via ``lax.ppermute`` (one ICI hop per step) while each device keeps an
online-softmax accumulator for its local queries — flash-attention
semantics distributed over the mesh, compute overlapping the permute.

This is the TPU-native answer to the reference's "long prompts stream
through chunked hashing" scope note (SURVEY §2.3): here long prompts
also *compute* in chunks, across chips.  Use under ``shard_map`` with
q/k/v sharded on the sequence axis, or via ``ring_attention`` which
wraps the shard_map given a mesh.

Performance note: contiguous chunking under causal masking is
load-imbalanced — device 0's queries are fully masked after one step
while the last device's stay visible every step.  ``striped=True``
selects the rebalanced layout (tokens interleave across devices via
``stripe``/``unstripe``; the causal mask becomes a near-uniform band
per step).  Two step bodies exist:

* ``impl="einsum"`` (the portable body; what ``"auto"`` picks off
  TPU): full Tq x Tk product + where() mask — balanced under striping
  but no FLOPs saved;
* ``impl="flash"``: each step runs the mask-aware Pallas partial
  (ops/ring_flash_pallas.py) whose K/V trip count stops at the causal
  diagonal, merged across steps by the flash-decoding combine.  With
  ``striped=True`` every step is a near-uniform causal band, so the
  layout's balance becomes ~half the per-step MXU work on every
  device; measured per-step on the chip by bench.py (detail.
  kernels.ring).

The model reaches both: ``forward(sp_mesh=..., ring_striped=True,
ring_impl="flash")`` runs the whole network in stripe order and
unstripes before the logits.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _shard_map(fn, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across the supported JAX range: newer releases
    export it at the top level (replication checker flag ``check_vma``),
    older ones only under ``jax.experimental.shard_map`` where the same
    flag is ``check_rep``."""
    top = getattr(jax, "shard_map", None)
    if top is not None:
        extra = {} if check_vma is None else {"check_vma": check_vma}
        return top(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **extra
        )
    from jax.experimental.shard_map import shard_map as legacy

    extra = {} if check_vma is None else {"check_rep": check_vma}
    return legacy(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **extra
    )


def resolve_auto_impl(
    mesh_platform: str,
    local_kv_tokens: int,
    head_dim: int,
    dtype_bytes: int,
    interpret: bool = False,
) -> str:
    """The step body ``impl="auto"`` resolves to, as a pure function.

    Flash is eligible only where the Pallas kernel will actually run
    (TPU mesh, or explicit interpret mode) AND the per-step K/V chunk
    fits the kernel's VMEM staging budget (``flash_pallas.fits_vmem``
    — the partial stages one kv-head's full local K and V chunk,
    ``2 * T_local * head_dim`` elements, in VMEM; past the budget the
    pallas_call fails to lower or silently spills).  Everything else
    falls back to the einsum body, which streams from HBM.  interpret
    mode is exempt from the bound: no real VMEM is allocated, and the
    flag is an explicit request to exercise the Pallas kernel.
    """
    from llm_d_kv_cache_manager_tpu.ops.flash_pallas import fits_vmem

    if interpret:
        return "flash"
    if mesh_platform != "tpu":
        return "einsum"
    if not fits_vmem(local_kv_tokens, head_dim, dtype_bytes):
        return "einsum"
    return "flash"


def _ring_driver(state, k, v, axis_name: str, accumulate):
    """Ring skeleton shared by both step bodies: K/V rotate around the
    ``axis_name`` ring via ppermute while ``accumulate(state, src,
    k_cur, v_cur)`` folds each chunk in; the last chunk accumulates
    outside the loop (no wasted final ppermute).  Keeping ONE driver
    means an overlap/permute change cannot silently apply to one body
    and not the other."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(i, carry):
        state, k_cur, v_cur = carry
        src = (my_idx - i) % axis_size  # ring position k_cur came from
        state = accumulate(state, src, k_cur, v_cur)
        return (
            state,
            lax.ppermute(k_cur, axis_name, perm),
            lax.ppermute(v_cur, axis_name, perm),
        )

    state, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, step, (state, k, v)
    )
    src_last = (my_idx - (axis_size - 1)) % axis_size
    return accumulate(state, src_last, k_last, v_last)


def _ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    striped: bool = False,
) -> jnp.ndarray:
    """Per-device body. q/k/v: [B, T_local, H(kv), D]; causal over the
    global sequence.

    ``striped=False``: chunk i holds contiguous positions
    [i*T_local, (i+1)*T_local).  ``striped=True``: chunk i holds the
    interleaved stripe {t : t % R == i} in ascending order (see
    ``stripe``), so local row a on chunk c is global position a*R + c —
    the causal mask becomes the near-uniform band ``b <= a - (src >
    my_idx)`` and every device does almost equal work at every ring
    step (the contiguous layout leaves early chunks idle once their
    queries are past all rotated keys)."""
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    groups = H // Hkv
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)

    qf = q.astype(jnp.float32).reshape(B, Tq, Hkv, groups, D) * (D**-0.5)

    # Derive accumulators from qf so they carry shard_map's
    # varying-manual-axes type (a fresh jnp.zeros would not).
    o = jnp.zeros_like(qf)
    zero = jnp.zeros_like(qf[..., 0]).transpose(0, 2, 3, 1)  # [B,Hkv,g,Tq]
    m = zero + NEG_INF
    l = zero

    def accumulate(state, src, k_cur, v_cur):
        o, m, l = state

        scores = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qf, k_cur.astype(jnp.float32)
        )
        if striped:
            # Global positions: query a*R + my_idx vs key b*R + src;
            # b*R + src <= a*R + my_idx  <=>  b <= a - (src > my_idx).
            mask = jnp.arange(Tk)[None, :] <= (
                jnp.arange(Tq)[:, None]
                - (src > my_idx).astype(jnp.int32)
            )
        else:
            q_pos = my_idx * Tq + jnp.arange(Tq)[:, None]
            k_pos = src * Tk + jnp.arange(Tk)[None, :]
            mask = k_pos <= q_pos  # [Tq, Tk] causal, global positions
        scores = jnp.where(mask[None, None, None], scores, NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        # Keep exp() away from the -inf sentinel when a chunk is fully
        # masked (fresh accumulator, future chunk): scale becomes exp(0).
        m_safe = jnp.maximum(m_new, 0.5 * NEG_INF)
        scale = jnp.exp(jnp.maximum(m, 0.5 * NEG_INF) - m_safe)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(mask[None, None, None], p, 0.0)

        l = l * scale + p.sum(axis=-1)
        o = o * scale.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
            "bhgqk,bkhd->bqhgd", p, v_cur.astype(jnp.float32)
        )
        return o, m_new, l

    o, m, l = _ring_driver((o, m, l), k, v, axis_name, accumulate)
    l = jnp.maximum(l, 1e-20)
    o = o / l.transpose(0, 3, 1, 2)[..., None]
    return o.reshape(B, Tq, H, D).astype(q.dtype)


def _ring_attention_local_flash(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    striped: bool = False,
    q_block: int = 256,
    kv_chunk: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Mask-aware per-device body: each ring step runs the Pallas
    flash PARTIAL (ops/ring_flash_pallas.py) whose K/V trip count
    stops at the causal diagonal, so masked sub-tiles are never
    computed — where the einsum body spends a full Tq x Tk product
    per step and discards the masked half with where().

    Per-step work:

    * striped — every step is a near-uniform causal band (offset 0 or
      -1): every device does ~half the product at every step. This is
      where the striped layout's balance becomes FLOPs saved.
    * contiguous — steps are fully-visible (full product), diagonal
      (causal half), or fully-masked (skipped outright); per-step
      wall-clock is still set by the busiest device, which is why the
      striped layout is the one that converts balance into time.

    GQA note: the partial kernel indexes K/V heads by q_head //
    (H // Hkv), so q/k/v arrive exactly as _qkv produces them.
    """
    from llm_d_kv_cache_manager_tpu.ops.ring_flash_pallas import (
        flash_partial,
        merge_partials,
        neutral_partial,
        normalize_partial,
    )

    my_idx = lax.axis_index(axis_name)

    partial_kw = dict(
        q_block=q_block, kv_chunk=kv_chunk, interpret=interpret
    )

    def step_partial(src, k_cur, v_cur):
        operand = (q, k_cur, v_cur)
        if striped:
            # Keys from behind me in the ring sit one global position
            # later at equal local rows: offset -1.
            return lax.cond(
                src > my_idx,
                lambda a: flash_partial(
                    *a, causal_offset=-1, **partial_kw
                ),
                lambda a: flash_partial(
                    *a, causal_offset=0, **partial_kw
                ),
                operand,
            )
        # Contiguous: 0 = fully visible, 1 = diagonal, 2 = fully masked.
        case = (src >= my_idx).astype(jnp.int32) + (
            src > my_idx
        ).astype(jnp.int32)
        return lax.switch(
            case,
            [
                lambda a: flash_partial(
                    *a, causal_offset=None, **partial_kw
                ),
                lambda a: flash_partial(
                    *a, causal_offset=0, **partial_kw
                ),
                lambda a: neutral_partial(a[0]),
            ],
            operand,
        )

    def accumulate(state, src, k_cur, v_cur):
        return merge_partials(state, step_partial(src, k_cur, v_cur))

    acc, _, l = _ring_driver(
        neutral_partial(q), k, v, axis_name, accumulate
    )
    return normalize_partial(acc, l, q.dtype)


def stripe(x: jnp.ndarray, ring_size: int, axis: int = 1) -> jnp.ndarray:
    """Permute a sequence axis into the striped ring layout: global
    token t goes to chunk t % ring_size, slot t // ring_size — so that
    sharding the result contiguously over the ring gives each device an
    interleaved stripe.  Static permutation (trace-time indices)."""
    T = x.shape[axis]
    if T % ring_size:
        raise ValueError(f"sequence {T} not divisible by ring {ring_size}")
    idx = np.arange(T).reshape(T // ring_size, ring_size).T.reshape(-1)
    return jnp.take(x, jnp.asarray(idx), axis=axis)


def unstripe(x: jnp.ndarray, ring_size: int, axis: int = 1) -> jnp.ndarray:
    """Inverse of :func:`stripe` — which is itself a stripe with the
    complementary factor (the permutation t -> (t % R)*(T/R) + t//R is
    inverted by the same map with R' = T/R)."""
    T = x.shape[axis]
    if T % ring_size:
        raise ValueError(f"sequence {T} not divisible by ring {ring_size}")
    return stripe(x, T // ring_size, axis=axis)


def ring_attention_sharded(
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = None,
    striped: bool = False,
    impl: str = "auto",
    interpret: bool = False,
):
    """The in-jit form: returns a callable ``(q, k, v) -> out`` over
    already-sharded [B, T, H(kv), D] arrays (T over ``axis_name``, B
    over ``batch_axis``).  Model code calls this inside its own jit —
    shard_map composes under jit; no device_put happens here.

    ``head_axis`` (e.g. ``"tp"``) shards the head dimension too — the
    tp×sp composition: each shard runs the ring over its own head
    slice (attention is head-independent; GQA group count is preserved
    since H and Hkv divide by the same degree).  Left None, heads are
    replicated over the mesh and tp-sharded inputs would be
    all-gathered per call.

    ``striped=True`` expects q/k/v already in the :func:`stripe` layout
    (and returns output in it — :func:`unstripe` after): the causal
    work balances across ring steps instead of concentrating on the
    last chunks.  RoPE/position embeddings must be applied BEFORE
    striping (or with striped position vectors) — positions are
    physical token indices, not stripe slots.

    ``impl``: ``"auto"`` picks ``"flash"`` on TPU and ``"einsum"``
    elsewhere; ``"einsum"`` is the portable full-product body;
    ``"flash"`` runs each step through the mask-aware Pallas partial
    (_ring_attention_local_flash) that skips masked sub-tiles — with
    ``striped=True`` this halves per-step MXU work.  ``interpret``
    runs the Pallas kernel in interpret mode (CPU tests)."""
    bspec = batch_axis if batch_axis else None
    spec = P(bspec, axis_name, head_axis, None)

    def build(resolved: str):
        check_vma = None
        if resolved == "flash":
            local = functools.partial(
                _ring_attention_local_flash,
                axis_name=axis_name,
                striped=striped,
                interpret=interpret,
            )
            # Pallas calls inside shard_map trip the vma checker (its
            # interpreter's internal slices don't pvary index
            # operands); JAX's own error message prescribes
            # check_vma=False.  Ring exactness is pinned by
            # tests/test_llama_model.py
            # (test_flash_ring_matches_dense_both_layouts) instead.
            check_vma = False
        elif resolved == "einsum":
            local = functools.partial(
                _ring_attention_local,
                axis_name=axis_name,
                striped=striped,
            )
        else:
            raise ValueError(f"unknown ring impl {resolved!r}")
        return _shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=check_vma,
        )

    if impl != "auto":
        return build(impl)

    # "auto": the mask-aware Pallas body where the kernel will actually
    # run (the MESH's platform — a CPU debug mesh on a TPU host must
    # not dispatch pltpu onto CPU devices) AND where each device's K/V
    # chunk fits the kernel's VMEM staging budget (resolve_auto_impl;
    # the shape is only known at call/trace time, hence the dispatch
    # wrapper).  interpret=True is an explicit request to exercise the
    # Pallas kernel, so it forces flash — silently resolving to einsum
    # would drop the flag and fake the coverage the caller asked for.
    mesh_platform = next(iter(mesh.devices.flat)).platform
    ring = mesh.shape[axis_name]
    built = {}

    def dispatch(q, k, v):
        resolved = resolve_auto_impl(
            mesh_platform,
            local_kv_tokens=k.shape[1] // ring,
            head_dim=k.shape[-1],
            dtype_bytes=jnp.dtype(k.dtype).itemsize,
            interpret=interpret,
        )
        if resolved not in built:
            built[resolved] = build(resolved)
        return built[resolved](q, k, v)

    return dispatch


def ring_for_mesh(
    sp_mesh: Mesh,
    striped: bool = False,
    impl: str = "auto",
    interpret: bool = False,
):
    """Model-layer convenience: the sharded ring with the standard
    axis gating — batch rides ``dp`` and heads ride ``tp`` when those
    axes exist with degree > 1 (declaring tp-sharded heads replicated
    would all-gather them every layer).  One helper so every model
    family (llama, moe, ...) gates identically."""

    def axis_if_used(name):
        return (
            name
            if name in sp_mesh.axis_names and sp_mesh.shape[name] > 1
            else None
        )

    return ring_attention_sharded(
        sp_mesh,
        batch_axis=axis_if_used("dp"),
        head_axis=axis_if_used("tp"),
        striped=striped,
        impl=impl,
        interpret=interpret,
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
    striped: bool = False,
    impl: str = "auto",
    interpret: bool = False,
) -> jnp.ndarray:
    """Eager convenience: place q/k/v ([B, T, H, D]; T sharded over
    ``axis_name``, B over ``batch_axis``) and run the ring.

    With ``striped=True`` the inputs/output are in PHYSICAL token order
    — this wrapper stripes them in, runs the balanced ring, and
    unstripes the output."""
    ring_size = mesh.shape[axis_name]
    if striped:
        q = stripe(q, ring_size)
        k = stripe(k, ring_size)
        v = stripe(v, ring_size)
    bspec = batch_axis if batch_axis else None
    spec = P(bspec, axis_name, None, None)
    fn = ring_attention_sharded(
        mesh,
        axis_name,
        batch_axis,
        striped=striped,
        impl=impl,
        interpret=interpret,
    )
    q = jax.device_put(q, NamedSharding(mesh, spec))
    k = jax.device_put(k, NamedSharding(mesh, spec))
    v = jax.device_put(v, NamedSharding(mesh, spec))
    out = fn(q, k, v)
    if striped:
        out = unstripe(out, ring_size)
    return out
