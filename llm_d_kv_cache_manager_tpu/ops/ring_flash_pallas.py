"""Pallas flash-attention PARTIALS for the ring (mask-aware steps).

One ring-attention step computes a local Tq x Tk attention product
whose mask is known per step (ops/ring_attention.py):

* striped layout — every step is a causal band over LOCAL rows,
  ``key_row <= query_row + offset`` with offset 0 or -1;
* contiguous layout — a step is fully visible, diagonal (causal), or
  fully masked.

The einsum body computes the full product and ``where()``-masks it, so
half the MXU work of a causal step is discarded.  This kernel instead
returns an UNNORMALIZED partial — accumulator plus the online-softmax
residuals (row max ``m``, row sum ``l``) — and stops its K/V trip
count at the causal diagonal, so a causal step does only the visible
half.  Ring steps merge partials with the standard log-sum-exp
combine (``merge_partials``) and normalize once at the end; the
flash-decoding decomposition, applied across ring steps.

Kernel idioms (VMEM scratch accumulators, lane-replicated m/l rows,
MXU dot_generals, tiled-axes-last layout) follow
ops/flash_pallas.py::_flash_kernel, which pins the same math for the
single-device prefill path.  Exactness vs the einsum ring body is
pinned by tests/test_llama_model.py (test_flash_ring_matches_dense_
both_layouts and friends; interpret mode on CPU).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Lane width of the m/l outputs.  The VMEM scratch stays at the native
# 128 lanes (flash_pallas.py idiom), but only lane 0 carries data — as
# HBM OUTPUTS a 128-wide copy would cost 2x the acc payload's traffic
# per ring step, so the store narrows to 8 lanes (16x less) and the
# wrapper slices lane 0.
ML_LANES = 8


def _partial_kernel(
    q_ref,  # [1, 1, q_block, D]
    k_ref,  # [1, 1, Tk_pad, D]
    v_ref,  # [1, 1, Tk_pad, D]
    acc_ref,  # out [1, 1, q_block, D] f32
    m_ref,  # out [1, 1, q_block, ML_LANES] f32
    l_ref,  # out [1, 1, q_block, ML_LANES] f32
    acc_scratch,  # VMEM [q_block, D] f32
    m_scratch,  # VMEM [q_block, 128] f32
    l_scratch,  # VMEM [q_block, 128] f32
    *,
    causal_offset: Optional[int],
    kv_len: int,
    q_block: int,
    kv_chunk: int,
    scale: float,
):
    qi = pl.program_id(2)
    q_start = qi * q_block  # LOCAL row of this tile's first query

    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale

    acc_scratch[...] = jnp.zeros_like(acc_scratch)
    m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
    l_scratch[...] = jnp.zeros_like(l_scratch)

    if causal_offset is None:
        n_chunks = pl.cdiv(kv_len, kv_chunk)
    else:
        # Last key any row of this tile may see:
        # q_start + q_block - 1 + causal_offset.
        last = jnp.clip(
            q_start + q_block + causal_offset, 0, kv_len
        )
        n_chunks = pl.cdiv(last, kv_chunk)

    row = jax.lax.broadcasted_iota(jnp.int32, (q_block, kv_chunk), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (q_block, kv_chunk), 1)

    def chunk_body(ci, _):
        k_start = ci * kv_chunk
        k = k_ref[0, 0, pl.ds(k_start, kv_chunk), :]
        v = v_ref[0, 0, pl.ds(k_start, kv_chunk), :]

        s = jax.lax.dot_general(
            q,
            k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [q_block, kv_chunk]

        k_pos = k_start + col
        mask = k_pos < kv_len  # zero out the kv_chunk padding
        if causal_offset is not None:
            q_pos = q_start + row
            mask &= k_pos <= q_pos + causal_offset
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Fully-masked rows keep m at NEG_INF; the guard keeps exp()
        # away from the sentinel (same idiom as the einsum ring body).
        m_safe = jnp.maximum(m_new, 0.5 * NEG_INF)
        p = jnp.exp(s - m_safe)
        p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(
            jnp.maximum(m_prev, 0.5 * NEG_INF) - m_safe
        )

        l_scratch[...] = l_scratch[...] * correction + jnp.sum(
            p, axis=1, keepdims=True
        )
        m_scratch[...] = jnp.broadcast_to(m_new, m_scratch.shape)
        acc_scratch[...] = acc_scratch[...] * correction + (
            jax.lax.dot_general(
                p,
                v.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        return 0

    jax.lax.fori_loop(0, n_chunks, chunk_body, 0)

    acc_ref[0, 0, :, :] = acc_scratch[...]
    m_ref[0, 0, :, :] = m_scratch[:, :ML_LANES]
    l_ref[0, 0, :, :] = l_scratch[:, :ML_LANES]


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal_offset", "q_block", "kv_chunk", "interpret"
    ),
)
def flash_partial(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal_offset: Optional[int] = 0,
    q_block: int = 256,
    kv_chunk: int = 512,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Unnormalized GQA flash partial over one K/V chunk.

    q: [B, Tq, H, D]; k/v: [B, Tk, Hkv, D].
    ``causal_offset``: keys visible to LOCAL row a are b <= a + offset
    (0: diagonal included; -1: strictly below — the striped ring's
    behind-me step).  ``None``: fully visible (no mask).
    Returns f32 ``(acc [B, Tq, H, D], m [B, Tq, H], l [B, Tq, H])``
    such that ``acc / l`` is the softmax output of this chunk alone
    and ``(m, l)`` merge across chunks via :func:`merge_partials`.
    """
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    groups = H // Hkv

    q_block = min(q_block, max(Tq, 8))
    kv_chunk = min(kv_chunk, Tk)
    q_pad = (-Tq) % q_block
    k_pad = (-Tk) % kv_chunk

    qt = jnp.pad(q, ((0, 0), (0, q_pad), (0, 0), (0, 0))).transpose(
        0, 2, 1, 3
    )
    kt = jnp.pad(k, ((0, 0), (0, k_pad), (0, 0), (0, 0))).transpose(
        0, 2, 1, 3
    )
    vt = jnp.pad(v, ((0, 0), (0, k_pad), (0, 0), (0, 0))).transpose(
        0, 2, 1, 3
    )
    nq = (Tq + q_pad) // q_block
    Tq_pad = Tq + q_pad

    kernel = functools.partial(
        _partial_kernel,
        causal_offset=causal_offset,
        kv_len=Tk,
        q_block=q_block,
        kv_chunk=kv_chunk,
        scale=D**-0.5,
    )
    kv_spec = pl.BlockSpec(
        (1, 1, Tk + k_pad, D),
        lambda b, h, qi, g=groups: (b, h // g, 0, 0),
        memory_space=pltpu.VMEM,
    )
    # Under shard_map with check_vma, outputs must declare how they
    # vary over the mesh — same as the inputs (the ring body runs
    # per-shard).
    try:
        vma = {"vma": jax.typeof(q).vma}
    except AttributeError:  # older jax: no vma tracking
        vma = {}
    acc, m, l = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, H, Tq_pad, D), jnp.float32, **vma),
            jax.ShapeDtypeStruct(
                (B, H, Tq_pad, ML_LANES), jnp.float32, **vma
            ),
            jax.ShapeDtypeStruct(
                (B, H, Tq_pad, ML_LANES), jnp.float32, **vma
            ),
        ),
        grid=(B, H, nq),
        in_specs=[
            pl.BlockSpec(
                (1, 1, q_block, D),
                lambda b, h, qi: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            kv_spec,
            kv_spec,
        ],
        out_specs=(
            pl.BlockSpec(
                (1, 1, q_block, D),
                lambda b, h, qi: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, q_block, ML_LANES),
                lambda b, h, qi: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, q_block, ML_LANES),
                lambda b, h, qi: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((q_block, D), jnp.float32),
            pltpu.VMEM((q_block, 128), jnp.float32),
            pltpu.VMEM((q_block, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    acc = acc.transpose(0, 2, 1, 3)  # [B, Tq_pad, H, D]
    m = m[..., 0].transpose(0, 2, 1)  # [B, Tq_pad, H]
    l = l[..., 0].transpose(0, 2, 1)
    if q_pad:
        acc, m, l = acc[:, :Tq], m[:, :Tq], l[:, :Tq]
    return acc, m, l


def neutral_partial(
    q: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The identity element of :func:`merge_partials` (a fully-masked
    step): zero accumulator, NEG_INF max, zero sum.  Derived from q so
    the values carry shard_map's varying manual axes."""
    acc = jnp.zeros_like(q, dtype=jnp.float32)
    zero = jnp.zeros_like(q[..., 0], dtype=jnp.float32)
    return acc, zero + NEG_INF, zero


def merge_partials(
    state: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
    update: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Log-sum-exp combine of two unnormalized partials (flash-
    decoding merge).  Both are ``(acc, m, l)`` with acc [..., D] and
    m/l [...]; associative, identity :func:`neutral_partial`."""
    acc_a, m_a, l_a = state
    acc_b, m_b, l_b = update
    m_new = jnp.maximum(m_a, m_b)
    m_safe = jnp.maximum(m_new, 0.5 * NEG_INF)
    s_a = jnp.exp(jnp.maximum(m_a, 0.5 * NEG_INF) - m_safe)
    s_b = jnp.exp(jnp.maximum(m_b, 0.5 * NEG_INF) - m_safe)
    return (
        acc_a * s_a[..., None] + acc_b * s_b[..., None],
        m_new,
        l_a * s_a + l_b * s_b,
    )


def normalize_partial(
    acc: jnp.ndarray, l: jnp.ndarray, dtype
) -> jnp.ndarray:
    """Final softmax division; fully-masked rows yield 0, not NaN."""
    return (acc / jnp.maximum(l, 1e-20)[..., None]).astype(dtype)
