"""Device-mesh and sharding helpers (TPU-first SPMD layout).

The serving fleet design point (BASELINE.json): each pod is a TPU slice
running the model under a single jitted SPMD program over a
`jax.sharding.Mesh`; the KV-cache manager stack above it is fleet-level
control plane.  This package owns the mesh/axis conventions shared by
the model, the paged KV pool, and the offload connector.
"""

from llm_d_kv_cache_manager_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    MeshPlan,
    make_mesh,
)

__all__ = [
    "AXIS_DP",
    "AXIS_PP",
    "AXIS_TP",
    "AXIS_SP",
    "AXIS_EP",
    "MeshPlan",
    "make_mesh",
]
