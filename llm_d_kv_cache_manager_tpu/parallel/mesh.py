"""Mesh construction and axis conventions.

Axis names (fixed across the framework so PartitionSpecs compose):

- ``dp``: data parallel — batch axis; gradients all-reduced over it.
- ``pp``: pipeline/stage axis — the stacked-layer axis of scanned
  decoder params is sharded over it (XLA turns the layer scan over a
  sharded leading axis into per-stage execution with collective
  permutes of the activations between stages).
- ``tp``: tensor parallel — attention heads and MLP hidden dim.
- ``sp``: sequence/context parallel — long-context prefill shards the
  sequence axis and runs ring attention over ``sp`` (ppermute over ICI).
- ``ep``: expert parallel — reserved for MoE model families; meshes are
  always built with the axis present (size 1 unless requested) so
  PartitionSpecs mentioning it are valid everywhere.

On real hardware ``jax.devices()`` for a TPU slice enumerates chips so
that adjacent devices are ICI neighbours; we put ``sp``/``tp`` innermost
so their collectives ride ICI, and ``dp`` outermost so it can span DCN
(multi-host data parallelism), mirroring how the reference fleet scales
pods over the datacenter network while NCCL stays intra-pod
(reference: vllm-setup-helm topology; scaling-book mesh recipe).
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"

# Outermost-to-innermost device ordering; see module docstring.
AXIS_ORDER: Tuple[str, ...] = (AXIS_DP, AXIS_PP, AXIS_EP, AXIS_SP, AXIS_TP)


@dataclass
class MeshPlan:
    """Requested parallelism degrees; -1 on ``dp`` means "absorb the
    remaining devices" (the common fleet configuration)."""

    dp: int = -1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {
            AXIS_DP: self.dp,
            AXIS_PP: self.pp,
            AXIS_TP: self.tp,
            AXIS_SP: self.sp,
            AXIS_EP: self.ep,
        }
        fixed = 1
        free_axes = [a for a, s in sizes.items() if s == -1]
        for a, s in sizes.items():
            if s != -1:
                if s <= 0:
                    raise ValueError(f"axis {a} has invalid size {s}")
                fixed *= s
        if len(free_axes) > 1:
            raise ValueError("at most one axis may be -1")
        if free_axes:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed product {fixed}"
                )
            sizes[free_axes[0]] = n_devices // fixed
        else:
            if fixed != n_devices:
                raise ValueError(
                    f"mesh plan wants {fixed} devices, have {n_devices}"
                )
        return sizes


@contextmanager
def activate(mesh: Mesh) -> Iterator[Mesh]:
    """Enter a mesh context so bare PartitionSpecs resolve (e.g. in
    ``lax.with_sharding_constraint``).

    Prefers ``jax.set_mesh`` (jax >= 0.6, the non-deprecated path: it
    also sets the abstract mesh, which ``with mesh:`` no longer does),
    falling back to the legacy ``with mesh:`` thread-resources context
    on older jax.  All framework entry points route through here so the
    choice lives in one place.
    """
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        with set_mesh(mesh):
            yield mesh
    else:
        with mesh:
            yield mesh


def mesh_is_active() -> bool:
    """Whether a PartitionSpec can currently resolve to mesh axes:
    either a ``jax.set_mesh`` scope (abstract mesh) or a legacy
    ``with mesh:`` context (thread-resources env).

    Model code uses this to make sharding constraints a deterministic
    no-op outside any mesh (single-device serving paths) instead of
    try/except-ing ``with_sharding_constraint``, which would silently
    bake a constraint-free trace into the jit cache under a mesh.
    """
    try:
        abstract = jax.sharding.get_abstract_mesh()
        if abstract is not None and not getattr(abstract, "empty", True):
            return True
    except Exception:  # noqa: BLE001 API drift; kvlint: disable=KV005
        # Capability probe: absence of the new-style API is an expected
        # state on older jax, not an error — fall through to the legacy
        # probe (a log here would fire on every trace).
        pass
    try:
        # ``with mesh:`` still routes through the legacy thread-resources
        # env (jax 0.9: get_abstract_mesh()/get_mesh() only see
        # jax.set_mesh).  The attribute works but warns; keep the probe
        # quiet until the legacy context manager loses the env entirely.
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            from jax.interpreters import pxla

            return not pxla.thread_resources.env.physical_mesh.empty
    except Exception:  # noqa: BLE001
        return False


def make_mesh(
    plan: Optional[MeshPlan] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh with the framework's canonical axis order."""
    devices = list(devices if devices is not None else jax.devices())
    plan = plan or MeshPlan()
    sizes = plan.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def make_hybrid_mesh(
    ici_plan: Optional[MeshPlan] = None,
    dcn_plan: Optional[MeshPlan] = None,
) -> Mesh:
    """Multi-host mesh: per-axis ICI (intra-slice) x DCN (cross-host)
    degrees, same canonical axis names.

    The fleet-scaling recipe: call ``jax.distributed.initialize()`` on
    every host of a multi-slice/multi-host deployment, then build the
    mesh here.  ``mesh_utils.create_hybrid_device_mesh`` orders devices
    so each axis's DCN factor crosses slice boundaries while its ICI
    factor stays inside a slice — collectives for ``tp``/``sp`` ride
    ICI, while ``dp`` (gradient all-reduce, the bandwidth-tolerant one)
    crosses DCN, mirroring how the reference's fleet keeps NCCL
    intra-pod and scales pods over the datacenter network.

    ``dcn_plan`` defaults to data-parallel over the process count
    (dp=n_processes) — the standard multi-host serving/training fleet.
    """
    from jax.experimental import mesh_utils

    devices = jax.devices()
    n_processes = max(d.process_index for d in devices) + 1
    per_slice = len(devices) // n_processes
    ici_plan = ici_plan or MeshPlan(dp=1, tp=per_slice)
    dcn_plan = dcn_plan or MeshPlan(dp=n_processes)
    ici_sizes = ici_plan.resolve(per_slice)
    dcn_sizes = dcn_plan.resolve(n_processes)
    if n_processes == 1:
        # Single host: hybrid degenerates to the flat ICI mesh.
        merged = MeshPlan(
            **{
                a: ici_sizes[a] * dcn_sizes[a]
                for a in (AXIS_DP, AXIS_PP, AXIS_TP, AXIS_SP, AXIS_EP)
            }
        )
        return make_mesh(merged, devices)
    # Granule = process: dcn degrees count hosts, matching this
    # function's contract on every backend (jax's default granule is
    # the TPU slice, which breaks single-slice multi-host deployments
    # and CPU clusters whose devices have no slice_index).
    dev_array = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=tuple(ici_sizes[a] for a in AXIS_ORDER),
        dcn_mesh_shape=tuple(dcn_sizes[a] for a in AXIS_ORDER),
        devices=devices,
        process_is_granule=True,
    )
    return Mesh(dev_array, AXIS_ORDER)
