"""Durable index persistence: snapshot + KV-event journal + recovery.

The global block-hash->pod index is rebuilt purely from live KVEvents,
so an indexer restart cold-starts routing across the whole fleet (the
bench's ``restart`` workload prices that at a ~10x hit-rate cliff).
This subsystem makes warm restarts possible for the in-process
backends:

* :mod:`snapshot` — atomic point-in-time dumps of any ``Index`` backend
  (versioned header, canonical-CBOR payload, CRC, tmp+rename publish).
* :mod:`journal` — an append-only log of applied index operations,
  tapped from the event pool's post-apply path, with segment rotation
  and per-pod sequence watermarks.
* :mod:`recovery` — the startup orchestrator: latest valid snapshot +
  journal-tail replay past the watermarks, torn tails tolerated.

``PersistenceManager`` composes the three; see docs/persistence.md for
the on-disk formats and crash-safety guarantees.
"""

from llm_d_kv_cache_manager_tpu.persistence.journal import (  # noqa: F401
    Journal,
    JournalRecord,
    OP_ADD,
    OP_EVICT,
    TailPosition,
    tail,
)
from llm_d_kv_cache_manager_tpu.persistence.recovery import (  # noqa: F401
    PersistenceConfig,
    PersistenceManager,
    RecoveryReport,
    recover,
)
from llm_d_kv_cache_manager_tpu.persistence.snapshot import (  # noqa: F401
    SnapshotInfo,
    load_latest_snapshot,
    write_snapshot,
)
