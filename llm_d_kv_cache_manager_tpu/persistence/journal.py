"""Append-only KV-event journal: segments, watermarks, torn-tail reads.

The journal records *applied index operations* (not raw wire events):
the event pool taps it immediately after ``index.add`` / ``index.evict``
succeeds, so replay needs no token re-hashing and no parent-block
resolution — a record replays as the exact index call it logs, which
makes replay idempotent and order-insensitive across pods (per-pod
order is preserved structurally: one pod always lands on one pool
shard, and appends happen in apply order).

Segment files ``segment-<id>.kvj`` (see docs/persistence.md):

    MAGIC(8) | version u16 BE
    repeated records: len u32 BE | crc32(body) u32 BE | body

``body`` is canonical CBOR:

    [op, pod, seq, ts_ns, engine_keys, request_keys,
     [[pod, tier], ...]]

with ``op`` 0=add, 1=evict (evict carries an empty request_keys list),
2=purge (an administrative ``purge_pod``; keys and entries empty, the
purged pod in the ``pod`` field — replay must not resurrect what an
operator dropped).
A reader stops at the first record that is short, oversized, or fails
CRC — the torn-tail contract: a crash mid-append loses at most the
record being written, never the ability to replay what preceded it.

Rotation: a segment is sealed once it exceeds ``segment_max_bytes``;
the writer then opens ``segment-<id+1>``.  A fresh ``Journal`` always
starts a NEW segment past the highest existing id — it never appends
to a file a previous process may have torn.  Compaction removes sealed
segments wholly covered by a published snapshot (see
``PersistenceManager.snapshot``'s rotate-then-dump ordering).

Watermarks: the journal tracks the highest publisher sequence number
appended per pod — the same per-pod seq stream the subscriber's
gap counters watch (``zmq_subscriber.py``).  Snapshots embed the
watermarks at their journal boundary; replay skips numbered records
strictly below them (equal-seq records replay — one message's events
share a seq and can straddle the boundary; unnumbered records, seq 0,
always replay.  Replay is idempotent either way).
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    CborDecodeError,
    decode_canonical,
    encode_canonical,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("persistence.journal")

MAGIC = b"KVTPUJNL"
FORMAT_VERSION = 1
_FILE_HEADER = struct.Struct(">8sH")
_RECORD_HEADER = struct.Struct(">II")  # body length, crc32(body)
SEGMENT_SUFFIX = ".kvj"

OP_ADD = 0
OP_EVICT = 1
# Administrative pod purge (Index.purge_pod): engine/request keys and
# entries are empty; ``pod_identifier`` names the purged pod.  Without
# this record a replay (recovery, replication followers) would
# resurrect entries an operator explicitly dropped.
OP_PURGE = 2

# A single record is a few KB at most (one BlockStored batch); anything
# bigger is framing corruption, treated like a torn tail.
MAX_RECORD_BYTES = 16 * 1024 * 1024

DEFAULT_SEGMENT_MAX_BYTES = 4 * 1024 * 1024


@dataclass
class JournalRecord:
    """One applied index operation."""

    op: int
    pod_identifier: str
    seq: int
    ts_ns: int
    engine_keys: List[int]
    request_keys: List[int]
    entries: List[PodEntry] = field(default_factory=list)

    def encode(self) -> bytes:
        return encode_canonical(
            [
                self.op,
                self.pod_identifier,
                self.seq,
                self.ts_ns,
                [int(k) for k in self.engine_keys],
                [int(k) for k in self.request_keys],
                [
                    [e.pod_identifier, e.device_tier]
                    for e in self.entries
                ],
            ]
        )

    @staticmethod
    def decode(body: bytes) -> "JournalRecord":
        doc = decode_canonical(body)
        if not isinstance(doc, list) or len(doc) != 7:
            raise CborDecodeError("unexpected journal record shape")
        op, pod, seq, ts_ns, engine_keys, request_keys, entries = doc
        return JournalRecord(
            op=int(op),
            pod_identifier=str(pod),
            seq=int(seq),
            ts_ns=int(ts_ns),
            engine_keys=[int(k) for k in engine_keys],
            request_keys=[int(k) for k in request_keys],
            entries=[PodEntry(str(p), str(t)) for p, t in entries],
        )


def _segment_path(directory: str, segment_id: int) -> str:
    return os.path.join(
        directory, f"segment-{segment_id:012d}{SEGMENT_SUFFIX}"
    )


def list_segments(directory: str) -> List[Tuple[int, str]]:
    """(id, path) of every segment on disk, oldest first."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    out: List[Tuple[int, str]] = []
    for name in names:
        if not name.startswith("segment-") or not name.endswith(
            SEGMENT_SUFFIX
        ):
            continue
        try:
            segment_id = int(name[len("segment-") : -len(SEGMENT_SUFFIX)])
        except ValueError:
            continue
        out.append((segment_id, os.path.join(directory, name)))
    out.sort()
    return out


def read_segment(path: str) -> Iterator[JournalRecord]:
    """Yield valid records; stop silently at the first torn/corrupt one.

    The stop-don't-skip policy is deliberate: resuming past a corrupt
    record could replay a later ``add`` whose preceding ``evict`` was
    lost, resurrecting entries the engine no longer holds.  Everything
    past the first bad byte is left to TTL/reconciler healing.
    """
    with open(path, "rb") as handle:
        header = handle.read(_FILE_HEADER.size)
        if len(header) < _FILE_HEADER.size:
            return
        magic, version = _FILE_HEADER.unpack(header)
        if magic != MAGIC or version != FORMAT_VERSION:
            logger.warning("foreign journal segment %s; skipping", path)
            return
        while True:
            rec_header = handle.read(_RECORD_HEADER.size)
            if len(rec_header) < _RECORD_HEADER.size:
                return  # clean EOF or torn header
            length, crc = _RECORD_HEADER.unpack(rec_header)
            if length > MAX_RECORD_BYTES:
                logger.warning(
                    "implausible record length %d in %s; stopping",
                    length,
                    path,
                )
                return
            body = handle.read(length)
            if len(body) < length:
                return  # torn body at the tail
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                logger.warning("CRC mismatch in %s; stopping", path)
                return
            try:
                yield JournalRecord.decode(body)
            except (CborDecodeError, TypeError, ValueError) as exc:
                logger.warning(
                    "undecodable record in %s (%s); stopping", path, exc
                )
                return


class Journal:
    """Thread-safe append-only journal writer over rotating segments."""

    def __init__(
        self,
        directory: str,
        segment_max_bytes: int = DEFAULT_SEGMENT_MAX_BYTES,
        fsync: bool = False,
    ) -> None:
        if segment_max_bytes <= 0:
            raise ValueError("segment_max_bytes must be positive")
        self.directory = directory
        self.segment_max_bytes = segment_max_bytes
        self.fsync = fsync
        os.makedirs(directory, exist_ok=True)
        existing = list_segments(directory)
        # Never append to a segment a dead process may have torn.
        self._segment_id = (existing[-1][0] + 1) if existing else 0  # guarded-by: _lock
        self._handle = None  # guarded-by: _lock
        self._segment_bytes = 0  # guarded-by: _lock
        self._watermarks: Dict[str, int] = {}  # guarded-by: _lock
        self._records_since_snapshot = 0  # guarded-by: _lock
        # Leaf lock: appends/rotations never acquire anything else
        # while holding it (index apply happens before the journal tap).
        self._lock = lockorder.tracked(threading.Lock(), "Journal._lock")

    # -- append path ---------------------------------------------------

    def record_add(
        self,
        pod_identifier: str,
        seq: int,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        self._append(
            JournalRecord(
                op=OP_ADD,
                pod_identifier=pod_identifier,
                seq=int(seq),
                ts_ns=time.time_ns(),
                engine_keys=list(engine_keys),
                request_keys=list(request_keys),
                entries=list(entries),
            )
        )

    def record_evict(
        self,
        pod_identifier: str,
        seq: int,
        engine_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        self._append(
            JournalRecord(
                op=OP_EVICT,
                pod_identifier=pod_identifier,
                seq=int(seq),
                ts_ns=time.time_ns(),
                engine_keys=list(engine_keys),
                request_keys=[],
                entries=list(entries),
            )
        )

    def record_purge(self, pod_identifier: str, seq: int = 0) -> None:
        self._append(
            JournalRecord(
                op=OP_PURGE,
                pod_identifier=pod_identifier,
                seq=int(seq),
                ts_ns=time.time_ns(),
                engine_keys=[],
                request_keys=[],
                entries=[],
            )
        )

    def _append(self, record: JournalRecord) -> None:
        body = record.encode()
        framed = (
            _RECORD_HEADER.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF)
            + body
        )
        with self._lock:
            handle = self._ensure_segment_locked()
            handle.write(framed)
            handle.flush()
            if self.fsync:
                os.fsync(handle.fileno())
            self._segment_bytes += len(framed)
            if record.seq > self._watermarks.get(
                record.pod_identifier, -1
            ):
                self._watermarks[record.pod_identifier] = record.seq
            self._records_since_snapshot += 1
            lag = self._records_since_snapshot
            if self._segment_bytes >= self.segment_max_bytes:
                self._rotate_locked()
        METRICS.persistence_journal_records.labels(
            op={OP_ADD: "add", OP_EVICT: "evict"}.get(
                record.op, "purge"
            )
        ).inc()
        METRICS.persistence_journal_lag.set(lag)

    def _ensure_segment_locked(self):
        if self._handle is None:
            path = _segment_path(self.directory, self._segment_id)
            self._handle = open(path, "ab")
            if self._handle.tell() == 0:
                self._handle.write(
                    _FILE_HEADER.pack(MAGIC, FORMAT_VERSION)
                )
                self._handle.flush()
            self._segment_bytes = self._handle.tell()
        return self._handle

    def _rotate_locked(self) -> int:
        """Seal the current segment; returns the NEW active segment id."""
        if self._handle is not None:
            self._handle.close()
            self._handle = None
        self._segment_id += 1
        self._segment_bytes = 0
        return self._segment_id

    # -- snapshot coordination ----------------------------------------

    def snapshot_boundary(self) -> Tuple[int, Dict[str, int], int]:
        """Atomically rotate; returns ``(boundary_id, watermarks,
        records_at_boundary)``.

        Every record in segments ``< boundary_id`` was appended — and
        therefore applied to the index — before this call returned, so
        a dump taken *after* it covers them all.  The watermark copy is
        taken under the same lock, so no record with a seq above it can
        live below the boundary.  The lag counter is NOT reset here:
        callers deduct ``records_at_boundary`` via
        :meth:`mark_snapshot_published` only once the snapshot write
        actually succeeds — a failed publish (ENOSPC is the likeliest
        persistence failure) must keep reporting the true replay cost.
        """
        with self._lock:
            boundary = self._rotate_locked()
            watermarks = dict(self._watermarks)
            lag_at_boundary = self._records_since_snapshot
        return boundary, watermarks, lag_at_boundary

    def mark_snapshot_published(self, covered: int) -> None:
        """Deduct ``covered`` records (the lag at the boundary of a
        snapshot that PUBLISHED) from the lag counter; appends that
        raced past the boundary stay counted (conservative)."""
        with self._lock:
            self._records_since_snapshot = max(
                0, self._records_since_snapshot - covered
            )
            lag = self._records_since_snapshot
        METRICS.persistence_journal_lag.set(lag)

    def compact_keep_last(self, retain_segments: int) -> int:
        """Delete all but the newest ``retain_segments`` segment files;
        returns segments removed.  Size-based retention for journals
        with no snapshot boundary to compact against (cluster replicas'
        replication feeds — docs/replication.md): a follower lagging
        past the retention window loses the deleted records (the tail
        cursor skips the hole) and should re-bootstrap.  The active
        segment is always within the retained suffix."""
        if retain_segments <= 0:
            return 0
        removed = 0
        for _, path in list_segments(self.directory)[:-retain_segments]:
            try:
                os.unlink(path)
                removed += 1
            except OSError:  # pragma: no cover - concurrent compactor
                pass
        if removed:
            logger.info(
                "retention-compacted %d journal segment(s) in %s",
                removed,
                self.directory,
            )
        return removed

    def compact_before(self, boundary_id: int) -> int:
        """Delete sealed segments with id < boundary_id; returns count."""
        removed = 0
        for segment_id, path in list_segments(self.directory):
            if segment_id >= boundary_id:
                continue
            try:
                os.unlink(path)
                removed += 1
            except OSError:  # pragma: no cover - concurrent compactor
                pass
        if removed:
            logger.info(
                "compacted %d journal segment(s) below %d",
                removed,
                boundary_id,
            )
        return removed

    # -- introspection -------------------------------------------------

    def watermarks(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._watermarks)

    def records_since_snapshot(self) -> int:
        with self._lock:
            return self._records_since_snapshot

    def close(self) -> None:
        with self._lock:
            if self._handle is not None:
                self._handle.close()
                self._handle = None


def iter_journal(
    directory: str, min_segment_id: int = 0
) -> Iterator[JournalRecord]:
    """Replay every surviving record, oldest segment first.  Segments
    below ``min_segment_id`` (a snapshot's journal boundary — fully
    covered by the dump) are skipped wholesale."""
    for segment_id, path in list_segments(directory):
        if segment_id < min_segment_id:
            continue
        yield from read_segment(path)


# -- follow API (replication followers; docs/replication.md) ------------


@dataclass(frozen=True)
class TailPosition:
    """A resumable cursor into a journal directory.

    ``offset == 0`` means the segment's file header has not been
    validated yet; otherwise it is the byte offset just past the last
    fully-consumed record.  Positions are plain data — safe to persist
    or ship between processes.  ``TailPosition(boundary_id, 0)`` starts
    a follow at a snapshot boundary (every record in segments
    ``< boundary_id`` is covered by the snapshot; see
    ``Journal.snapshot_boundary``).
    """

    segment_id: int
    offset: int = 0


def tail(
    directory: str,
    position: Optional[TailPosition] = None,
    max_records: int = 0,
) -> Tuple[List[JournalRecord], TailPosition]:
    """Read records appended since ``position``; returns
    ``(records, new_position)``.

    The follow contract (regression-pinned in
    tests/test_journal_tail.py):

    * **Torn tails hold, they don't lose.**  A partial record at the
      end of the ACTIVE (highest-id) segment — the writer's append may
      be partially visible — leaves the cursor at the last complete
      record; the next call re-reads from there and returns the record
      once it is whole.  In a SEALED segment (a higher-id segment
      exists) a torn or corrupt record can never complete: the rest of
      that segment is abandoned (same stop-don't-skip policy as
      ``read_segment``, logged) and the cursor moves to the next
      segment.
    * **Rotation is seamless.**  Clean EOF on a sealed segment advances
      to the next segment id present on disk; gaps in the id sequence
      (compaction, or a sealed segment deleted mid-follow) are skipped
      to the smallest surviving id.
    * **Decode-bad records skip.**  A CRC-valid record that fails CBOR
      decoding is fully written and will never change; holding would
      wedge the follower forever, so it is skipped with a warning.

    ``position=None`` starts at the oldest segment on disk.
    ``max_records`` bounds one call (0 = unbounded); a bounded call may
    return mid-segment and resumes exactly where it stopped.
    """
    segments = list_segments(directory)
    if position is None:
        start_id = segments[0][0] if segments else 0
        position = TailPosition(start_id, 0)
    if not segments:
        return [], position

    records: List[JournalRecord] = []
    segment_id = position.segment_id
    offset = position.offset
    latest_id = segments[-1][0]
    by_id = dict(segments)
    while True:
        if max_records and len(records) >= max_records:
            break
        path = by_id.get(segment_id)
        if path is None:
            successors = [sid for sid in by_id if sid > segment_id]
            if not successors:
                break  # nothing (yet) at or past the cursor
            segment_id = min(successors)
            offset = 0
            continue
        sealed = segment_id < latest_id
        consumed, segment_records, exhausted = _read_from(
            path,
            offset,
            max_records - len(records) if max_records else 0,
        )
        records.extend(segment_records)
        offset = consumed
        if not exhausted:
            break  # record budget reached mid-segment
        if not sealed:
            break  # active segment: hold at the last complete record
        # Sealed: whatever stopped us (clean EOF, torn tail, corrupt
        # record) can never change — move on.
        segment_id += 1
        offset = 0
    return records, TailPosition(segment_id, offset)


def _read_from(
    path: str, offset: int, max_records: int
) -> Tuple[int, List[JournalRecord], bool]:
    """Read complete records from ``offset``; returns
    ``(new_offset, records, exhausted)`` where ``exhausted`` means the
    stop was the segment itself (EOF/torn/corrupt), not the record
    budget.  ``new_offset`` never advances past a record that failed to
    read completely."""
    records: List[JournalRecord] = []
    try:
        handle = open(path, "rb")
    except FileNotFoundError:  # compacted between listing and open
        return offset, records, True
    with handle:
        if offset == 0:
            header = handle.read(_FILE_HEADER.size)
            if len(header) < _FILE_HEADER.size:
                return 0, records, True  # header not fully visible yet
            magic, version = _FILE_HEADER.unpack(header)
            if magic != MAGIC or version != FORMAT_VERSION:
                logger.warning(
                    "foreign journal segment %s in follow; skipping", path
                )
                # Report exhausted with the cursor parked at EOF-ish;
                # a sealed foreign file is skipped by the caller, an
                # active one holds (and is re-checked, staying cheap).
                return 0, records, True
            offset = _FILE_HEADER.size
        else:
            handle.seek(offset)
        while True:
            if max_records and len(records) >= max_records:
                return offset, records, False
            rec_header = handle.read(_RECORD_HEADER.size)
            if len(rec_header) < _RECORD_HEADER.size:
                return offset, records, True  # clean EOF or torn header
            length, crc = _RECORD_HEADER.unpack(rec_header)
            if length > MAX_RECORD_BYTES:
                logger.warning(
                    "implausible record length %d in %s at %d; stopping",
                    length,
                    path,
                    offset,
                )
                return offset, records, True
            body = handle.read(length)
            if len(body) < length:
                return offset, records, True  # torn body
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                logger.warning(
                    "CRC mismatch in %s at %d; stopping", path, offset
                )
                return offset, records, True
            consumed = offset + _RECORD_HEADER.size + length
            try:
                records.append(JournalRecord.decode(body))
            except (CborDecodeError, TypeError, ValueError) as exc:
                # Fully written (CRC passed) — will never change;
                # holding would wedge the follower forever.
                logger.warning(
                    "undecodable record in %s at %d (%s); skipping",
                    path,
                    offset,
                    exc,
                )
            offset = consumed
