"""Warm-restart recovery orchestration + the PersistenceManager facade.

Recovery sequence (``recover``):

1. Load the newest *valid* snapshot (corrupt candidates fall back to
   older ones; none at all is a cold start).
2. ``index.restore_entries`` the dump through the backend's normal
   admission path (capacity bounds hold).
3. Replay the journal oldest-segment-first, skipping numbered records
   at or below the snapshot's per-pod watermark; stop at the first
   torn/corrupt record (``journal.read_segment``'s stop-don't-skip
   contract).
4. Return a :class:`RecoveryReport`.  Pods restored from disk may have
   changed state while the indexer was down — reconciliation of those
   *stale pods is deliberately NOT done here*: the existing machinery
   (the pod reconciler dropping dead pods' subscriptions plus
   ``Index.purge_pod``, and LRU/TTL churn) already owns that, and the
   report's ``pods`` list is exactly the input it needs.

``PersistenceManager`` owns the directory layout::

    <dir>/snapshots/snapshot-<ns>.snap
    <dir>/journal/segment-<id>.kvj

and the rotate -> dump -> publish -> compact snapshot ordering whose
correctness argument lives in ``Journal.snapshot_boundary``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.persistence.journal import (
    DEFAULT_SEGMENT_MAX_BYTES,
    OP_ADD,
    OP_PURGE,
    Journal,
    iter_journal,
)
from llm_d_kv_cache_manager_tpu.persistence.snapshot import (
    SnapshotInfo,
    load_latest_snapshot,
    write_snapshot,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("persistence.recovery")

# snapshot() holds _snapshot_lock across the journal boundary/compact
# calls, the index dump, and the _info_lock publish — the snapshot lock
# is the root of the persistence hierarchy.  Declared for KV006 and the
# runtime watchdog alike.
# kvlint: lock-order: PersistenceManager._snapshot_lock < Journal._lock
lockorder.declare_order(
    "PersistenceManager._snapshot_lock", "Journal._lock"
)
# kvlint: lock-order: PersistenceManager._snapshot_lock < PersistenceManager._info_lock
lockorder.declare_order(
    "PersistenceManager._snapshot_lock", "PersistenceManager._info_lock"
)
# kvlint: lock-order: PersistenceManager._snapshot_lock < LRUCache._lock
lockorder.declare_order(
    "PersistenceManager._snapshot_lock", "LRUCache._lock"
)
# kvlint: lock-order: PersistenceManager._snapshot_lock < CostAwareMemoryIndex._lock
lockorder.declare_order(
    "PersistenceManager._snapshot_lock", "CostAwareMemoryIndex._lock"
)


@dataclass
class PersistenceConfig:
    """Layout + durability knobs for the persistence subsystem."""

    directory: str
    journal_segment_max_bytes: int = DEFAULT_SEGMENT_MAX_BYTES
    # Journal fsync per record: off by default — a lost tail only
    # widens the replay gap the TTL/reconciler machinery tolerates.
    journal_fsync: bool = False
    snapshots_retained: int = 2

    @property
    def snapshot_dir(self) -> str:
        return os.path.join(self.directory, "snapshots")

    @property
    def journal_dir(self) -> str:
        return os.path.join(self.directory, "journal")


@dataclass
class RecoveryReport:
    """What a warm (or cold) start actually restored."""

    status: str  # "warm" | "cold"
    snapshot_path: Optional[str] = None
    snapshot_created_ns: Optional[int] = None
    block_keys_restored: int = 0
    engine_mappings_restored: int = 0
    records_replayed: int = 0
    records_skipped: int = 0
    pods: List[str] = field(default_factory=list)
    duration_s: float = 0.0

    def to_dict(self) -> dict:
        return {
            "status": self.status,
            "snapshot_path": self.snapshot_path,
            "snapshot_age_s": (
                round(
                    max(time.time_ns() - self.snapshot_created_ns, 0)
                    / 1e9,
                    1,
                )
                if self.snapshot_created_ns
                else None
            ),
            "block_keys_restored": self.block_keys_restored,
            "engine_mappings_restored": self.engine_mappings_restored,
            "records_replayed": self.records_replayed,
            "records_skipped": self.records_skipped,
            "pods": self.pods,
            "duration_s": round(self.duration_s, 3),
        }


def recover(index: Index, config: PersistenceConfig) -> RecoveryReport:
    """Warm-restart ``index`` from disk; see module docstring."""
    start = time.perf_counter()
    report = RecoveryReport(status="cold")
    pods: Dict[str, None] = {}  # ordered de-dup

    if getattr(index, "durable_backend", False):
        # The backend's state outlives the process (Redis/Valkey) and
        # is shared by sibling replicas: restoring a file snapshot or
        # replaying the journal would resurrect entries evicted or
        # purged server-side since the dump — the server IS the warm
        # state.  (The old dump_entries no-op used to make this
        # implicit; the gate became explicit when the backend was
        # promoted to the full dump/restore contract.)
        report.duration_s = time.perf_counter() - start
        METRICS.persistence_recoveries.labels(outcome="durable").inc()
        logger.info(
            "recovery skipped: %s is a durable backend (server state "
            "is authoritative; docs/persistence.md §6)",
            type(index).__name__,
        )
        return report

    watermarks: Dict[str, int] = {}
    min_segment_id = 0
    loaded = load_latest_snapshot(config.snapshot_dir)
    if loaded is not None:
        info, block_entries, engine_map = loaded
        if info.journal_boundary is not None:
            # Segments below the boundary are fully covered by the
            # dump: skipping them wholesale is both cheaper and
            # CORRECT where per-record idempotence is not — an
            # uncompacted pre-boundary OP_PURGE would otherwise replay
            # against restored state whose covering re-adds the
            # watermark skip elides.
            min_segment_id = info.journal_boundary
        report.block_keys_restored = index.restore_entries(
            block_entries, engine_map
        )
        report.engine_mappings_restored = len(engine_map)
        report.snapshot_path = info.path
        report.snapshot_created_ns = info.created_ns
        report.status = "warm"
        watermarks = info.watermarks
        for _, entries in block_entries:
            for entry in entries:
                pods.setdefault(entry.pod_identifier, None)

    for record in iter_journal(
        config.journal_dir, min_segment_id=min_segment_id
    ):
        watermark = watermarks.get(record.pod_identifier)
        # Strictly below only: one message's events share one seq, and
        # a record with seq == watermark can have been appended AFTER
        # the boundary capture while a sibling record of the same
        # message landed before it (the dump then lacks this record's
        # effect).  Equal-seq replay is idempotent; skipping it would
        # silently drop that applied op.
        if (
            watermark is not None
            and record.seq > 0
            and record.seq < watermark
        ):
            report.records_skipped += 1
            continue
        try:
            if record.op == OP_ADD:
                if record.engine_keys and record.entries:
                    index.add(
                        record.engine_keys,
                        record.request_keys,
                        record.entries,
                    )
            elif record.op == OP_PURGE:
                # An operator/resync purge between the snapshot and the
                # crash: replaying the adds without it would resurrect
                # exactly the entries the purge dropped.
                index.purge_pod(record.pod_identifier)
            else:
                for engine_key in record.engine_keys:
                    index.evict(engine_key, record.entries)
        except (KeyError, ValueError) as exc:
            # A replayed op can race LRU bounds (its parent already
            # re-evicted); per-record skip, same as the live pool.
            logger.debug("skipping unreplayable record: %s", exc)
            continue
        pods.setdefault(record.pod_identifier, None)
        report.records_replayed += 1

    if report.records_replayed:
        report.status = "warm"  # journal-only starts still count
    report.pods = list(pods)
    report.duration_s = time.perf_counter() - start
    METRICS.persistence_recoveries.labels(outcome=report.status).inc()
    METRICS.persistence_replayed_records.inc(report.records_replayed)
    logger.info(
        "recovery %s: %d block keys + %d journal records (%d skipped) "
        "across %d pods in %.3fs",
        report.status,
        report.block_keys_restored,
        report.records_replayed,
        report.records_skipped,
        len(report.pods),
        report.duration_s,
    )
    return report


class PersistenceManager:
    """Composes journal + snapshots over one directory tree."""

    def __init__(self, config: PersistenceConfig) -> None:
        self.config = config
        self.journal = Journal(
            config.journal_dir,
            segment_max_bytes=config.journal_segment_max_bytes,
            fsync=config.journal_fsync,
        )
        self._snapshot_lock = lockorder.tracked(
            threading.Lock(), "PersistenceManager._snapshot_lock"
        )
        # Separate from _snapshot_lock (held across the whole
        # dump+fsync): /healthz reads must never block on a slow
        # snapshot publish.
        self._info_lock = lockorder.tracked(
            threading.Lock(), "PersistenceManager._info_lock"
        )
        self.last_snapshot: Optional[SnapshotInfo] = None  # guarded-by: _info_lock

    def recover(self, index: Index) -> RecoveryReport:
        """Run recovery into ``index``.

        Call BEFORE wiring the journal into a live event pool: replay
        must not interleave with fresh appends into the same files.
        (The Journal itself already writes to a fresh segment, so this
        is about report coherence, not corruption.)
        """
        return recover(index, self.config)

    def snapshot(self, index: Index) -> SnapshotInfo:
        """Publish a snapshot of ``index`` and compact covered segments.

        Ordering: rotate the journal first (boundary + watermarks under
        one lock), THEN dump — every record below the boundary is
        already applied and therefore inside the dump; records above it
        survive compaction and replay idempotently.
        """
        with self._snapshot_lock:
            boundary, watermarks, covered = (
                self.journal.snapshot_boundary()
            )
            block_entries, engine_map = index.dump_entries()
            info = write_snapshot(
                self.config.snapshot_dir,
                watermarks,
                block_entries,
                engine_map,
                retain=self.config.snapshots_retained,
                journal_boundary=boundary,
            )
            self.journal.compact_before(boundary)
            self.journal.mark_snapshot_published(covered)
            with self._info_lock:
                self.last_snapshot = info
        METRICS.persistence_snapshot_timestamp.set(info.created_ns / 1e9)
        METRICS.persistence_snapshot_bytes.set(info.size_bytes)
        logger.info(
            "published snapshot %s (%d block keys, %d bytes)",
            info.path,
            info.block_keys,
            info.size_bytes,
        )
        return info

    def status(self) -> dict:
        """Health-endpoint view: snapshot age + journal lag."""
        with self._info_lock:
            info = self.last_snapshot
        return {
            "snapshot_path": info.path if info else None,
            "snapshot_age_s": (
                round(
                    max(time.time_ns() - info.created_ns, 0) / 1e9, 1
                )
                if info
                else None
            ),
            "snapshot_bytes": info.size_bytes if info else None,
            "journal_records_since_snapshot": (
                self.journal.records_since_snapshot()
            ),
        }

    def start_auto_snapshot(
        self, index: Index, interval_seconds: float = 300.0
    ) -> threading.Event:
        """Periodic snapshots on a daemon thread; returns a stop event
        (same shape as ``metrics.start_metrics_logging``)."""
        stop = threading.Event()

        def beat() -> None:
            while not stop.wait(interval_seconds):
                try:
                    self.snapshot(index)
                except Exception:  # noqa: BLE001 — beat must survive
                    logger.exception("periodic snapshot failed")

        thread = threading.Thread(
            target=beat, name="kvtpu-snapshot-beat", daemon=True
        )
        thread.start()
        return stop

    def close(self) -> None:
        self.journal.close()
