"""Atomic index snapshots: versioned header, canonical CBOR, tmp+rename.

On-disk layout (see docs/persistence.md):

    MAGIC(8) | version u16 BE | crc32(body) u32 BE | len(body) u64 BE | body

``body`` is one canonical-CBOR document (the same deterministic encoder
the block-hash contract uses, ``kvblock/cbor_canonical.py``):

    [created_ns, [[pod, seq], ...], [[request_key, [[pod, tier], ...]],
     ...], [[engine_key, request_key], ...]]

Crash safety follows the ``native/`` file-I/O discipline: the writer
builds the whole file at a ``.tmp.<pid>.<tid>`` path, fsyncs, then
``os.replace``s it into place — a reader can never observe a partial
snapshot under its final name, and the loader's CRC + length checks
reject any torn file a crashed writer might leave if it died *during*
the rename-capable window on a non-atomic filesystem.  Tmp litter from
killed writers never matches the snapshot glob and is swept on the next
successful publish.

Snapshots are named ``snapshot-<created_ns>.snap``; the loader walks
newest-first and returns the first file that validates, so one corrupt
latest snapshot degrades to the previous one, never to a crash.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    CborDecodeError,
    decode_canonical,
    encode_canonical,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("persistence.snapshot")

MAGIC = b"KVTPUSNP"
FORMAT_VERSION = 1
_HEADER = struct.Struct(">8sHIQ")  # magic, version, crc32, body length
SNAPSHOT_SUFFIX = ".snap"

# Defensive bound for the loader: a corrupt length field must not drive
# a multi-GB allocation.  Generous for real indexes (a 2 GiB-budget
# cost-aware dump is well under this).
MAX_SNAPSHOT_BYTES = 8 * 1024 * 1024 * 1024


class SnapshotError(ValueError):
    """A snapshot file failed validation (torn, corrupt, or foreign)."""


@dataclass
class SnapshotInfo:
    """Metadata of one published or loaded snapshot."""

    path: str
    created_ns: int
    size_bytes: int
    block_keys: int
    engine_mappings: int
    watermarks: Dict[str, int]
    # Journal boundary at the dump (Journal.snapshot_boundary): every
    # record in segments < this id is covered by the snapshot.  None
    # for snapshots written before the field existed (or without a
    # journal); recovery then falls back to replay-everything.
    journal_boundary: Optional[int] = None


def _encode_body(
    created_ns: int,
    watermarks: Dict[str, int],
    block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
    engine_map: Sequence[Tuple[int, int]],
    journal_boundary: Optional[int],
) -> bytes:
    doc = [
        created_ns,
        [[pod, int(seq)] for pod, seq in sorted(watermarks.items())],
        [
            [
                int(request_key),
                [[e.pod_identifier, e.device_tier] for e in pods],
            ]
            for request_key, pods in block_entries
        ],
        [[int(ek), int(rk)] for ek, rk in engine_map],
    ]
    if journal_boundary is not None:
        # Optional 5th element (decoder accepts 4 or 5): segments below
        # this journal id are fully covered by the snapshot, so
        # recovery skips them wholesale — without it, an uncompacted
        # pre-boundary OP_PURGE could replay against restored state
        # whose covering re-adds the watermark skip elides.
        doc.append(int(journal_boundary))
    return encode_canonical(doc)


def write_snapshot(
    directory: str,
    watermarks: Dict[str, int],
    block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
    engine_map: Sequence[Tuple[int, int]],
    retain: int = 2,
    journal_boundary: Optional[int] = None,
) -> SnapshotInfo:
    """Publish a snapshot atomically; prunes to the ``retain`` newest.

    The returned info's ``path`` is the final published name.  fsync on
    both the file and its directory entry: after this returns, the
    snapshot survives power loss (the journal's weaker flush-only
    default is acceptable because a lost journal tail only widens the
    replay gap the TTL/reconciler machinery already tolerates; a torn
    *snapshot* would lose the whole baseline).
    """
    os.makedirs(directory, exist_ok=True)
    created_ns = time.time_ns()
    body = _encode_body(
        created_ns, watermarks, block_entries, engine_map,
        journal_boundary,
    )
    header = _HEADER.pack(
        MAGIC, FORMAT_VERSION, zlib.crc32(body) & 0xFFFFFFFF, len(body)
    )
    final = os.path.join(
        directory, f"snapshot-{created_ns:020d}{SNAPSHOT_SUFFIX}"
    )
    tmp = f"{final}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as handle:
            handle.write(header)
            handle.write(body)
            handle.flush()
            os.fsync(handle.fileno())
        os.replace(tmp, final)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    _fsync_dir(directory)
    _prune(directory, retain=max(retain, 1), keep=final)
    return SnapshotInfo(
        path=final,
        created_ns=created_ns,
        size_bytes=len(header) + len(body),
        block_keys=len(block_entries),
        engine_mappings=len(engine_map),
        watermarks=dict(watermarks),
        journal_boundary=journal_boundary,
    )


def _fsync_dir(directory: str) -> None:
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def _candidates(directory: str) -> List[str]:
    """Published snapshot paths, newest first (name embeds created_ns).

    ``.tmp.*`` litter from killed writers never matches the suffix
    filter — the "partial tmp file never loaded" guarantee is
    structural, not a validation pass."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    return [
        os.path.join(directory, name)
        for name in sorted(names, reverse=True)
        if name.startswith("snapshot-") and name.endswith(SNAPSHOT_SUFFIX)
    ]


def _prune(directory: str, retain: int, keep: str) -> None:
    for stale in _candidates(directory)[retain:]:
        if stale == keep:  # never the one just published
            continue
        try:
            os.unlink(stale)
        except OSError:  # pragma: no cover - concurrent pruner
            pass
    # Sweep tmp litter from crashed writers (never loadable, but it
    # leaks disk one orphan per kill).
    try:
        names = os.listdir(directory)
    except FileNotFoundError:  # pragma: no cover
        return
    for name in names:
        if ".tmp." in name:
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:  # pragma: no cover
                pass


def read_snapshot(
    path: str,
) -> Tuple[
    SnapshotInfo,
    List[Tuple[int, List[PodEntry]]],
    List[Tuple[int, int]],
]:
    """Validate and decode one snapshot file.

    Raises :class:`SnapshotError` on any structural problem — short
    header, wrong magic, unknown version, length/CRC mismatch (a torn
    or bit-rotted file), or a body that decodes to the wrong shape.
    """
    with open(path, "rb") as handle:
        header = handle.read(_HEADER.size)
        if len(header) < _HEADER.size:
            raise SnapshotError(f"{path}: truncated header")
        magic, version, crc, length = _HEADER.unpack(header)
        if magic != MAGIC:
            raise SnapshotError(f"{path}: bad magic {magic!r}")
        if version != FORMAT_VERSION:
            raise SnapshotError(f"{path}: unsupported version {version}")
        if length > MAX_SNAPSHOT_BYTES:
            raise SnapshotError(f"{path}: implausible length {length}")
        body = handle.read(length)
    if len(body) != length:
        raise SnapshotError(
            f"{path}: torn body ({len(body)} of {length} bytes)"
        )
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise SnapshotError(f"{path}: CRC mismatch")
    try:
        doc = decode_canonical(body)
    except CborDecodeError as exc:
        raise SnapshotError(f"{path}: undecodable body: {exc}") from exc
    if not isinstance(doc, list) or len(doc) not in (4, 5):
        raise SnapshotError(f"{path}: unexpected document shape")
    created_ns, raw_watermarks, raw_entries, raw_engine_map = doc[:4]
    raw_boundary = doc[4] if len(doc) == 5 else None
    try:
        watermarks = {
            str(pod): int(seq) for pod, seq in raw_watermarks
        }
        block_entries = [
            (
                int(request_key),
                [PodEntry(str(pod), str(tier)) for pod, tier in pods],
            )
            for request_key, pods in raw_entries
        ]
        engine_map = [(int(ek), int(rk)) for ek, rk in raw_engine_map]
        journal_boundary = (
            int(raw_boundary) if raw_boundary is not None else None
        )
    except (TypeError, ValueError) as exc:
        raise SnapshotError(f"{path}: type-confused body: {exc}") from exc
    info = SnapshotInfo(
        path=path,
        created_ns=int(created_ns),
        size_bytes=_HEADER.size + length,
        block_keys=len(block_entries),
        engine_mappings=len(engine_map),
        watermarks=watermarks,
        journal_boundary=journal_boundary,
    )
    return info, block_entries, engine_map


def load_latest_snapshot(
    directory: str,
) -> Optional[
    Tuple[
        SnapshotInfo,
        List[Tuple[int, List[PodEntry]]],
        List[Tuple[int, int]],
    ]
]:
    """The newest snapshot that validates, or None (cold start).

    A corrupt newest file logs and falls back to the next — recovery
    prefers an older baseline plus a longer journal replay over
    refusing to start."""
    for path in _candidates(directory):
        try:
            return read_snapshot(path)
        except (SnapshotError, OSError) as exc:
            logger.warning("skipping invalid snapshot %s: %s", path, exc)
    return None
