from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (  # noqa: F401
    ApplyChatTemplateRequest,
    ChatTemplatingProcessor,
)
