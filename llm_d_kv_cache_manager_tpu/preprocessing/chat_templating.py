"""Chat-template preprocessing.

Chat-completions requests must be rendered to the *exact* prompt string the
serving engine will tokenize, or block hashes diverge and the hit rate
silently zeroes.  The reference pays a heavy tax for this — a Go process
embedding a CPython interpreter through cgo to reach
``tokenizer.apply_chat_template`` (pkg/preprocessing/chat_completions/,
~950 LoC across three languages; SURVEY §7.2 calls it the biggest
complexity tax).  This framework's host language is Python, so the same
capability is a direct call into ``transformers``; tokenizers are cached
per ``(model, revision, is_local)`` like the reference's wrapper
(tokenizer_wrapper.py:104-118).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ApplyChatTemplateRequest:
    """Mirror of the OpenAI chat-completions preprocessing surface
    (reference: cgo_functions.go:51-62)."""

    conversation: List[Dict[str, Any]] = field(default_factory=list)
    tools: Optional[List[Dict[str, Any]]] = None
    documents: Optional[List[Dict[str, Any]]] = None
    chat_template: Optional[str] = None
    add_generation_prompt: bool = True
    continue_final_message: bool = False
    chat_template_kwargs: Optional[Dict[str, Any]] = None
    model: Optional[str] = None
    revision: Optional[str] = None


class ChatTemplatingProcessor:
    """Renders chat conversations to prompt strings via transformers."""

    def __init__(self) -> None:
        self._tokenizers: Dict[str, Any] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def tokenizer_key(
        self, model: str, revision: Optional[str] = None
    ) -> str:
        return f"{model}:{revision or 'main'}"

    def _get_tokenizer(self, model: str, revision: Optional[str]):
        key = self.tokenizer_key(model, revision)
        with self._lock:
            tokenizer = self._tokenizers.get(key)
        if tokenizer is None:
            from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
                load_auto_tokenizer,
            )

            loaded = load_auto_tokenizer(model, revision=revision)
            with self._lock:
                # Two threads may both load; setdefault re-decides
                # under the lock so the first insert wins and both
                # callers share one instance.  # kvlint: atomic-ok
                tokenizer = self._tokenizers.setdefault(key, loaded)
        return tokenizer

    def register_tokenizer(
        self, model: str, tokenizer, revision: Optional[str] = None
    ) -> None:
        """Inject a pre-built tokenizer (local models, tests)."""
        with self._lock:
            self._tokenizers[self.tokenizer_key(model, revision)] = tokenizer

    def apply_chat_template(
        self, model: str, request: ApplyChatTemplateRequest
    ) -> str:
        """Render to a prompt string (never tokenized here — the
        tokenization pool owns that, with add_special_tokens=False)."""
        tokenizer = self._get_tokenizer(
            request.model or model, request.revision
        )
        kwargs: Dict[str, Any] = dict(request.chat_template_kwargs or {})
        return tokenizer.apply_chat_template(
            request.conversation,
            tools=request.tools,
            documents=request.documents,
            chat_template=request.chat_template,
            add_generation_prompt=request.add_generation_prompt,
            continue_final_message=request.continue_final_message,
            tokenize=False,
            **kwargs,
        )
