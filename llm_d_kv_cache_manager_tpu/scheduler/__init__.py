"""Inference-scheduler integration (reference: examples/kv_cache_aware_scorer)."""

from llm_d_kv_cache_manager_tpu.scheduler.precise_scorer import (
    ChatCompletionsBody,
    ChatMessage,
    CompletionsBody,
    LLMRequest,
    Pod,
    PrecisePrefixCacheScorer,
    PrecisePrefixCacheScorerConfig,
)

__all__ = [
    "ChatCompletionsBody",
    "ChatMessage",
    "CompletionsBody",
    "LLMRequest",
    "Pod",
    "PrecisePrefixCacheScorer",
    "PrecisePrefixCacheScorerConfig",
]
